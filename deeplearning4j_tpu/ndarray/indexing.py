"""NDArray indexing (reference: org/nd4j/linalg/indexing/ —
NDArrayIndex, INDArrayIndex impls {PointIndex, IntervalIndex, NDArrayIndexAll,
NewAxis, SpecifiedIndex}; consumed by INDArray#get/#put).

Index objects resolve to numpy-style index pieces; `get` is a pure
gather (a jax slice view), `put` is a functional scatter returning the
updated buffer wrapped by the SAME NDArray (matching the reference's
in-place put semantics at the API level — see ndarray.py's versioned
buffer note).
"""

from __future__ import annotations

from typing import Any, List, Sequence


class INDArrayIndex:
    """Marker base (reference: indexing/INDArrayIndex)."""

    def resolve(self):  # -> numpy-style index piece
        raise NotImplementedError


class PointIndex(INDArrayIndex):
    def __init__(self, i: int):
        self.i = int(i)

    def resolve(self):
        return self.i


class IntervalIndex(INDArrayIndex):
    def __init__(self, begin: int, end: int, stride: int = 1,
                 inclusive: bool = False):
        self.begin = int(begin)
        self.end = int(end) + (1 if inclusive else 0)
        self.stride = int(stride)

    def resolve(self):
        return slice(self.begin, self.end, self.stride)


class NDArrayIndexAll(INDArrayIndex):
    def resolve(self):
        return slice(None)


class NewAxis(INDArrayIndex):
    def resolve(self):
        return None  # numpy newaxis


class SpecifiedIndex(INDArrayIndex):
    def __init__(self, *indices: int):
        self.indices = [int(i) for i in indices]

    def resolve(self):
        import numpy as np

        return np.asarray(self.indices)


class NDArrayIndex:
    """Static factory (reference: indexing/NDArrayIndex)."""

    @staticmethod
    def all() -> INDArrayIndex:
        return NDArrayIndexAll()

    @staticmethod
    def point(i: int) -> INDArrayIndex:
        return PointIndex(i)

    @staticmethod
    def interval(begin: int, *args, stride: int = 1,
                 inclusive: bool = False) -> INDArrayIndex:
        """Reference overloads, argument order preserved EXACTLY:
        interval(begin, end) / interval(begin, stride, end[, inclusive]).
        Keyword form interval(begin, end, stride=..., inclusive=...)
        also accepted."""
        if len(args) == 1:
            end = args[0]
        elif len(args) in (2, 3):
            # 3-positional is the reference's (begin, STRIDE, end)
            stride, end = args[0], args[1]
            if len(args) == 3:
                inclusive = bool(args[2])
        else:
            raise TypeError(
                "interval(begin, end) or interval(begin, stride, end"
                "[, inclusive])")
        return IntervalIndex(begin, end, stride, inclusive)

    @staticmethod
    def newAxis() -> INDArrayIndex:
        return NewAxis()

    @staticmethod
    def indices(*idx: int) -> INDArrayIndex:
        return SpecifiedIndex(*idx)


def resolve_indices(idxs: Sequence[Any]) -> tuple:
    """INDArrayIndex / int / slice / list mix -> numpy index tuple."""
    out: List[Any] = []
    for ix in idxs:
        if isinstance(ix, INDArrayIndex):
            out.append(ix.resolve())
        else:
            out.append(ix)
    return tuple(out)
