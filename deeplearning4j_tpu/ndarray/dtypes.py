"""Data type calculus (reference: org.nd4j.linalg.api.buffer.DataType).

The reference enumerates dtypes in Java and mirrors them across JNI into
libnd4j's ``sd::DataType``. Here dtypes are jax/numpy dtypes with a thin
enum veneer preserving the reference's names, plus the promotion rules
the eager API needs. TPU note: bfloat16 is first-class (MXU-native);
float16 exists for parity but bf16 is the preferred reduced precision.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    """Mirror of nd4j's DataType enum, mapped onto jax dtypes."""

    DOUBLE = "float64"
    FLOAT = "float32"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    LONG = "int64"
    INT = "int32"
    SHORT = "int16"
    BYTE = "int8"
    UBYTE = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    BOOL = "bool"

    @property
    def jax(self) -> jnp.dtype:
        return jnp.dtype(self.value)

    @property
    def np(self) -> np.dtype:
        # bfloat16 has no numpy builtin; jnp.dtype handles the ml_dtypes ext.
        return jnp.dtype(self.value)

    def is_float(self) -> bool:
        return self in _FLOATS

    def is_int(self) -> bool:
        return self in _INTS

    def is_signed(self) -> bool:
        return self in _SIGNED

    def width_bytes(self) -> int:
        return jnp.dtype(self.value).itemsize

    @staticmethod
    def from_any(dtype) -> "DataType":
        """Coerce a DataType / jax dtype / numpy dtype / string to
        DataType. Strings also accept the common short aliases
        ("bf16", "fp16", "half", "f32", ...) so every CLI/bench/config
        shares ONE spelling table instead of hand-rolled maps."""
        if isinstance(dtype, DataType):
            return dtype
        if isinstance(dtype, str):
            alias = _DTYPE_ALIASES.get(dtype.strip().lower())
            if alias is not None:
                return alias
        name = jnp.dtype(dtype).name
        for dt in DataType:
            if dt.value == name:
                return dt
        raise ValueError(f"Unsupported dtype: {dtype!r}")


#: short-form spellings accepted by from_any (benches, configs, CLIs)
_DTYPE_ALIASES = {
    "bf16": DataType.BFLOAT16,
    "fp16": DataType.HALF,
    "f16": DataType.HALF,
    "half": DataType.HALF,
    "f32": DataType.FLOAT,
    "fp32": DataType.FLOAT,
    # NOTE: no "float" entry — numpy's 'float' means float64 and
    # from_any must keep that long-standing behavior
    "single": DataType.FLOAT,
    "f64": DataType.DOUBLE,
    "fp64": DataType.DOUBLE,
    "double": DataType.DOUBLE,
}


_FLOATS = {DataType.DOUBLE, DataType.FLOAT, DataType.HALF, DataType.BFLOAT16}
_INTS = {
    DataType.LONG,
    DataType.INT,
    DataType.SHORT,
    DataType.BYTE,
    DataType.UBYTE,
    DataType.UINT16,
    DataType.UINT32,
    DataType.UINT64,
}
_SIGNED = _FLOATS | {DataType.LONG, DataType.INT, DataType.SHORT, DataType.BYTE}

#: Default floating point type. The reference defaults to FLOAT (float32);
#: we keep that for eager/correctness paths. Training configs opt into
#: bfloat16 compute where the MXU benefits (see nn/conf dtype policy).
DEFAULT_FLOAT = DataType.FLOAT
