"""Activation functions (reference: org/nd4j/linalg/activations/** —
Activation enum + IActivation impls, SURVEY.md §2.17).

Each activation is a named pure-jax fn from the op registry; `Activation`
mirrors the reference enum and resolves to the fn. Used by layer configs
via string or enum (JSON stores the string).
"""

from __future__ import annotations

import enum
from typing import Callable

from deeplearning4j_tpu.ops.registry import get_op


class Activation(enum.Enum):
    """Reference: org.nd4j.linalg.activations.Activation."""

    IDENTITY = "identity"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    MISH = "mish"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    CUBE = "cube"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "recttanh"
    THRESHOLDEDRELU = "thresholdedrelu"

    @property
    def fn(self) -> Callable:
        if self is Activation.IDENTITY:
            return lambda x: x
        return get_op(self.value)

    @staticmethod
    def resolve(a) -> "Activation":
        if isinstance(a, Activation):
            return a
        if isinstance(a, str):
            return Activation[a.upper()] if a.upper() in Activation.__members__ \
                else Activation(a.lower())
        raise ValueError(f"Cannot resolve activation: {a!r}")


__all__ = ["Activation"]
