"""Clustering + nearest-neighbor structures — the reference's
``deeplearning4j-nearestneighbors-parent`` module family.

Reference (eclipse/deeplearning4j monorepo,
``deeplearning4j/deeplearning4j-nearestneighbors-parent/``):

- ``nearestneighbor-core/.../org/deeplearning4j/clustering/kmeans/
  KMeansClustering.java`` + ``cluster/{Point,Cluster,ClusterSet,
  ClusterUtils}.java`` + ``algorithm/BaseClusteringAlgorithm.java`` —
  Lloyd's k-means over pluggable distance functions with
  iteration-count / distribution-variation termination.
- ``.../clustering/vptree/VPTree.java`` — vantage-point tree used by
  word2vec ``wordsNearest`` and t-SNE.
- ``.../clustering/kdtree/KDTree.java`` — axis-split tree with
  ``nearest``/``knn``.
- ``deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer
  .java`` — REST k-NN over a stored matrix.
- ``.../clustering/sptree,quadtree`` serve the reference's Barnes-Hut
  t-SNE; this framework's t-SNE deliberately computes the exact O(N²)
  interaction ON DEVICE (see ``nlp/tsne.py``), so those host trees
  have no role here.

TPU-first redesign
------------------
The reference walks trees point-by-point on the JVM. Here every
distance computation is a BATCHED matrix op: k-means runs one compiled
XLA step per Lloyd iteration ([N,K] distance matrix on the MXU, argmin
assignment, segment-sum centroid update, empty-cluster reseed — all
inside one ``jit``), and tree queries compute vantage/axis distances
with vectorised numpy. For TPU-resident data the honest fast path for
k-NN is brute force on the MXU (``knn_brute``: one matmul + top_k beats
pointer chasing at any N that fits in HBM); the VP/KD trees are kept
for the reference's host-side API surface and for sublinear CPU
queries, and their results are pinned against ``knn_brute`` in tests.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------
# Distance functions (reference: ClusterUtils + Distance enum)
# ---------------------------------------------------------------------

def _pairwise(x: jnp.ndarray, c: jnp.ndarray, distance: str) -> jnp.ndarray:
    """[N,K] distances between rows of x [N,D] and c [K,D]."""
    if distance == "euclidean":
        # |x-c|^2 = |x|^2 - 2<x,c> + |c|^2 — one MXU matmul
        d2 = ((x * x).sum(-1, keepdims=True)
              - 2.0 * x @ c.T + (c * c).sum(-1)[None, :])
        return jnp.sqrt(jnp.maximum(d2, 0.0))
    if distance == "manhattan":
        return jnp.abs(x[:, None, :] - c[None, :, :]).sum(-1)
    if distance in ("cosinedistance", "cosinesimilarity", "cosine"):
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        cn = c / (jnp.linalg.norm(c, axis=-1, keepdims=True) + 1e-12)
        return 1.0 - xn @ cn.T
    if distance == "dot":
        return -(x @ c.T)
    raise ValueError(f"unknown distance function: {distance!r}")


DISTANCES = ("euclidean", "manhattan", "cosinedistance", "dot")


# ---------------------------------------------------------------------
# Cluster model (reference: clustering/cluster/*.java)
# ---------------------------------------------------------------------

class Point:
    """reference: cluster/Point.java — id + vector."""

    def __init__(self, point_id, array):
        self.id = point_id
        self.array = np.asarray(array, np.float32)

    @staticmethod
    def toPoints(matrix) -> List["Point"]:
        return [Point(i, row) for i, row in enumerate(np.asarray(matrix))]


class Cluster:
    def __init__(self, center: np.ndarray, cluster_id: int):
        self.id = cluster_id
        self.center = np.asarray(center, np.float32)
        self.points: List[Point] = []

    def getCenter(self) -> np.ndarray:
        return self.center

    def getPoints(self) -> List[Point]:
        return self.points


class ClusterSet:
    """reference: cluster/ClusterSet.java — the applyTo result."""

    def __init__(self, clusters: List[Cluster], distance: str):
        self.clusters = clusters
        self.distance = distance

    def getClusters(self) -> List[Cluster]:
        return self.clusters

    def getClusterCount(self) -> int:
        return len(self.clusters)

    def centers(self) -> np.ndarray:
        return np.stack([c.center for c in self.clusters])

    def classifyPoint(self, array) -> int:
        """Nearest-cluster id for one vector (reference:
        ClusterSet#classifyPoint)."""
        d = np.asarray(_pairwise(
            jnp.asarray(np.asarray(array, np.float32)[None, :]),
            jnp.asarray(self.centers()), self.distance))[0]
        return int(d.argmin())


# ---------------------------------------------------------------------
# K-means — one compiled step per Lloyd iteration
# ---------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("distance",))
def _kmeans_step(x, centers, distance):
    """assign -> recompute -> reseed-empty, all on device.

    Empty clusters take the globally farthest-from-assigned-center
    point (the reference's ClusterUtils empty-cluster repair)."""
    d = _pairwise(x, centers, distance)              # [N,K]
    assign = d.argmin(-1)                            # [N]
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=x.dtype)
    counts = onehot.sum(0)                           # [K]
    sums = onehot.T @ x                              # [K,D]
    new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
    # reseed: rank points by distance to their center, give the r-th
    # empty cluster the r-th farthest point
    mine = jnp.take_along_axis(d, assign[:, None], 1)[:, 0]
    order = jnp.argsort(-mine)                       # farthest first
    empty = counts == 0
    rank = jnp.cumsum(empty) - 1                     # r-th empty
    seed_pts = x[order[jnp.clip(rank, 0, x.shape[0] - 1)]]
    new_centers = jnp.where(empty[:, None], seed_pts, new_centers)
    distortion = (mine * mine).mean()
    return assign, new_centers, distortion


class KMeansClustering:
    """Lloyd's k-means (reference: kmeans/KMeansClustering.java —
    ``setup(clusterCount, maxIterationCount, distanceFunction)`` and the
    distribution-variation-rate termination variant). Centers start
    k-means++ (D² sampling) rather than the reference's uniform pick —
    same API, strictly better seeding."""

    def __init__(self, cluster_count: int, max_iterations: int = 100,
                 distance: str = "euclidean",
                 min_distribution_variation_rate: float = 1e-4,
                 seed: int = 0):
        if distance not in DISTANCES and distance not in (
                "cosinesimilarity", "cosine"):
            raise ValueError(f"unknown distance function: {distance!r}")
        self.k = int(cluster_count)
        self.max_iterations = max_iterations
        self.distance = distance
        self.min_variation = min_distribution_variation_rate
        self.seed = seed
        self.iterations_done = 0

    @staticmethod
    def setup(cluster_count: int, max_iterations: int = 100,
              distance: str = "euclidean", *,
              seed: int = 0) -> "KMeansClustering":
        return KMeansClustering(cluster_count, max_iterations, distance,
                                seed=seed)

    def _init_centers(self, x: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        n = x.shape[0]
        centers = [x[rng.integers(n)]]
        for _ in range(1, self.k):
            d2 = np.min(
                np.asarray(_pairwise(jnp.asarray(x),
                                     jnp.asarray(np.stack(centers)),
                                     "euclidean")) ** 2, axis=1)
            total = d2.sum()
            if total <= 0:       # fewer distinct points than k
                centers.append(x[rng.integers(n)])
            else:
                centers.append(x[rng.choice(n, p=d2 / total)])
        return np.stack(centers)

    def applyTo(self, points) -> ClusterSet:
        """Cluster a [N,D] matrix or a list of Points (reference:
        BaseClusteringAlgorithm#applyTo)."""
        if isinstance(points, (list, tuple)) and points \
                and isinstance(points[0], Point):
            ids = [p.id for p in points]
            x = np.stack([p.array for p in points]).astype(np.float32)
        else:
            x = np.asarray(points, np.float32)
            ids = list(range(x.shape[0]))
        if x.shape[0] < self.k:
            raise ValueError(
                f"need at least k={self.k} points, got {x.shape[0]}")
        xj = jnp.asarray(x)
        centers = jnp.asarray(self._init_centers(x))
        prev = np.inf
        for it in range(self.max_iterations):
            _, centers, distortion = _kmeans_step(
                xj, centers, self.distance)
            distortion = float(distortion)
            self.iterations_done = it + 1
            # converge only on a small NON-NEGATIVE improvement: a
            # transient distortion INCREASE (right after an
            # empty-cluster reseed moved a center) used to satisfy
            # `prev - distortion <= eps` too and ended Lloyd iterations
            # one reseed too early — keep optimizing through it
            if np.isfinite(prev) and \
                    0.0 <= prev - distortion <= self.min_variation * prev:
                break
            prev = distortion
        # final assignment against the RETURNED centers — the step's
        # assignment predates its center update, and pairing stale
        # assignments with new centers breaks classifyPoint consistency
        centers_np = np.asarray(centers)
        assign_np = np.asarray(
            _pairwise(xj, centers, self.distance).argmin(-1))
        clusters = [Cluster(centers_np[c], c) for c in range(self.k)]
        for i, c in enumerate(assign_np):
            clusters[c].points.append(Point(ids[i], x[i]))
        return ClusterSet(clusters, self.distance)


# ---------------------------------------------------------------------
# Brute-force k-NN — the TPU fast path the trees are pinned against
# ---------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k", "distance"))
def _knn_device(items, targets, k, distance):
    d = _pairwise(targets, items, distance)          # [Q,N]
    neg, idx = jax.lax.top_k(-d, k)
    return idx, -neg


def knn_brute(items, targets, k: int,
              distance: str = "euclidean"):
    """Batched exact k-NN: one [Q,N] distance matrix + top_k on device.
    Returns (indices [Q,k], distances [Q,k]). k is clamped to [1, N]."""
    items = jnp.asarray(np.asarray(items, np.float32))
    k = max(1, min(int(k), items.shape[0]))
    t = np.asarray(targets, np.float32)
    squeeze = t.ndim == 1
    if squeeze:
        t = t[None, :]
    idx, dist = _knn_device(items, jnp.asarray(t), int(k), distance)
    idx, dist = np.asarray(idx), np.asarray(dist)
    return (idx[0], dist[0]) if squeeze else (idx, dist)


class _BestK:
    """Candidate accumulator shared by both tree searches: keeps the k
    best (index, distance) pairs, exposes the pruning radius ``tau``."""

    def __init__(self, k: int):
        self.k = k
        self.idx: List[int] = []
        self.d: List[float] = []
        self.tau = np.inf

    def consider(self, idx: np.ndarray, d: np.ndarray) -> None:
        for i, di in zip(idx, d):
            if len(self.idx) < self.k or di < self.tau:
                self.idx.append(int(i))
                self.d.append(float(di))
        if len(self.idx) > self.k:
            order = np.argsort(self.d)[:self.k]
            self.idx = [self.idx[o] for o in order]
            self.d = [self.d[o] for o in order]
        if len(self.idx) == self.k:
            self.tau = max(self.d)

    def result(self) -> Tuple[np.ndarray, np.ndarray]:
        order = np.argsort(self.d)
        return (np.array([self.idx[o] for o in order]),
                np.array([self.d[o] for o in order]))


# ---------------------------------------------------------------------
# VPTree (reference: clustering/vptree/VPTree.java)
# ---------------------------------------------------------------------

class VPTree:
    """Vantage-point tree. Build partitions by median distance to a
    random vantage point; search prunes with the triangle inequality.
    Pruning requires a true metric, so euclidean/manhattan queries run
    the tree and every other distance transparently falls back to the
    brute-force device path (same results, documented divergence from
    the reference, whose cosine 'VPTree' quietly over-prunes)."""

    _LEAF = 16

    def __init__(self, items, distance: str = "euclidean", seed: int = 0):
        self.items = np.asarray(items, np.float32)
        if self.items.ndim != 2 or not len(self.items):
            raise ValueError("items must be a non-empty [N,D] matrix")
        self.distance = distance
        self._metric = distance in ("euclidean", "manhattan")
        if self._metric:
            self._rng = np.random.default_rng(seed)
            self._root = self._build(np.arange(len(self.items)))

    def _dist(self, a: np.ndarray, idx: np.ndarray) -> np.ndarray:
        if self.distance == "euclidean":
            return np.linalg.norm(self.items[idx] - a, axis=1)
        return np.abs(self.items[idx] - a).sum(1)

    def _build(self, idx: np.ndarray):
        if len(idx) <= self._LEAF:
            return ("leaf", idx)
        vp = idx[self._rng.integers(len(idx))]
        rest = idx[idx != vp]
        d = self._dist(self.items[vp], rest)
        mu = float(np.median(d))
        inner, outer = rest[d <= mu], rest[d > mu]
        if not len(inner) or not len(outer):       # degenerate split
            return ("leaf", idx)
        return ("node", vp, mu, self._build(inner), self._build(outer))

    def search(self, target, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, distances) of the k nearest items."""
        target = np.asarray(target, np.float32)
        k = min(k, len(self.items))
        if not self._metric:
            return knn_brute(self.items, target, k, self.distance)
        best = _BestK(k)

        def walk(node):
            if node[0] == "leaf":
                best.consider(node[1], self._dist(target, node[1]))
                return
            _, vp, mu, inner, outer = node
            dvp = float(self._dist(target, np.array([vp]))[0])
            best.consider(np.array([vp]), np.array([dvp]))
            near, far = (inner, outer) if dvp <= mu else (outer, inner)
            walk(near)
            if abs(dvp - mu) <= best.tau:          # triangle inequality
                walk(far)

        walk(self._root)
        return best.result()


# ---------------------------------------------------------------------
# KDTree (reference: clustering/kdtree/KDTree.java)
# ---------------------------------------------------------------------

class KDTree:
    """Axis-cycling median-split k-d tree; euclidean metric (the
    reference's KDTree is euclidean-only too)."""

    _LEAF = 16

    def __init__(self, items):
        self.items = np.asarray(items, np.float32)
        if self.items.ndim != 2 or not len(self.items):
            raise ValueError("items must be a non-empty [N,D] matrix")
        self._root = self._build(np.arange(len(self.items)), 0)

    def _build(self, idx: np.ndarray, depth: int):
        if len(idx) <= self._LEAF:
            return ("leaf", idx)
        axis = depth % self.items.shape[1]
        vals = self.items[idx, axis]
        order = np.argsort(vals, kind="stable")
        mid = len(idx) // 2
        split = float(vals[order[mid]])
        left, right = idx[order[:mid]], idx[order[mid:]]
        if not len(left) or not len(right):
            return ("leaf", idx)
        return ("node", axis, split,
                self._build(left, depth + 1),
                self._build(right, depth + 1))

    def nearest(self, target) -> Tuple[int, float]:
        idx, d = self.knn(target, 1)
        return int(idx[0]), float(d[0])

    def knn(self, target, k: int) -> Tuple[np.ndarray, np.ndarray]:
        target = np.asarray(target, np.float32)
        k = min(k, len(self.items))
        best = _BestK(k)

        def walk(node):
            if node[0] == "leaf":
                best.consider(node[1], np.linalg.norm(
                    self.items[node[1]] - target, axis=1))
                return
            _, axis, split, left, right = node
            near, far = (left, right) if target[axis] < split \
                else (right, left)
            walk(near)
            if abs(target[axis] - split) <= best.tau:
                walk(far)

        walk(self._root)
        return best.result()


# ---------------------------------------------------------------------
# NearestNeighborsServer (reference:
# deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer.java)
# ---------------------------------------------------------------------

class _KnnModel:
    """Adapter giving a k-NN index the .output() surface
    JsonModelServer serves."""

    def __init__(self, items, distance: str, default_k: int):
        self.items = np.asarray(items, np.float32)
        self.distance = distance
        self.default_k = default_k

    def output(self, payload):
        point, k = payload
        idx, dist = knn_brute(self.items, point,
                              k or self.default_k, self.distance)
        return idx, dist


class NearestNeighborsServer:
    """REST k-NN over a stored matrix, reusing the JsonModelServer
    plumbing: POST /v1/serving/predict
    ``{"point": [...], "k": 5}`` -> ``{"output": [indices, distances]}``
    (the reference serves POST /knn with the same contract)."""

    def __init__(self, items, distance: str = "euclidean",
                 default_k: int = 5, port: int = 0):
        from deeplearning4j_tpu.remote.server import JsonModelServer

        def input_adapter(payload: dict):
            if "point" not in payload:
                raise ValueError("payload must contain 'point'")
            return (np.asarray(payload["point"], np.float32),
                    int(payload.get("k", 0)))

        def output_adapter(out):
            idx, dist = out
            return [np.asarray(idx).tolist(),
                    np.asarray(dist).tolist()]

        self._server = JsonModelServer(
            _KnnModel(items, distance, default_k), port=port,
            input_adapter=input_adapter, output_adapter=output_adapter)

    def start(self) -> int:
        return self._server.start()

    def stop(self) -> None:
        self._server.stop()

    @property
    def port(self):
        return self._server.port
