"""Early stopping: train until a validation-score condition fires.

Reference surface (org/deeplearning4j/earlystopping/**):
``EarlyStoppingConfiguration`` (builder), epoch/iteration termination
conditions, ``ScoreCalculator`` impls, model savers, and
``EarlyStoppingTrainer`` producing an ``EarlyStoppingResult``.

TPU-native notes: the per-epoch fit is the compiled whole-step path of
``MultiLayerNetwork``/``ComputationGraph`` (one XLA executable per
step); early stopping is pure host-side control flow around it, so
nothing here traces into jit.
"""

from __future__ import annotations

import copy
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence


# ----------------------------------------------------------------------
# termination conditions
# ----------------------------------------------------------------------
class EpochTerminationCondition:
    """Checked after each epoch (ref: EpochTerminationCondition).

    ``requires_score``: score-based conditions are only consulted on
    epochs where the score calculator actually ran (otherwise a stale
    score would, e.g., count phantom no-improvement epochs)."""

    requires_score = True

    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no improvement greater than min_improvement
    (ref: ScoreImprovementEpochTerminationCondition)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.max_epochs_without_improvement = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best: Optional[float] = None
        self._epochs_since = 0

    def initialize(self) -> None:
        self._best = None
        self._epochs_since = 0

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        if self._best is None:
            self._best = score
            return False
        improvement = (self._best - score) if minimize else (score - self._best)
        if improvement > self.min_improvement:
            self._best = score
            self._epochs_since = 0
            return False
        self._epochs_since += 1
        return self._epochs_since >= self.max_epochs_without_improvement


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as a target value."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = best_expected_score

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        if minimize:
            return score < self.best_expected_score
        return score > self.best_expected_score


class IterationTerminationCondition:
    """Checked after each iteration (ref: IterationTerminationCondition)."""

    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self) -> None:
        self._start = time.time()

    def terminate(self, last_score: float) -> bool:
        if self._start is None:
            self._start = time.time()
        return (time.time() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if the minibatch loss explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)


# ----------------------------------------------------------------------
# score calculators
# ----------------------------------------------------------------------
class ScoreCalculator:
    """Computes the validation score for model selection
    (ref: org/deeplearning4j/earlystopping/scorecalc/ScoreCalculator)."""

    def calculate_score(self, model) -> float:
        raise NotImplementedError

    def minimize_score(self) -> bool:
        return True


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a validation iterator
    (ref: DataSetLossCalculator — average flag)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            k = ds.numExamples()
            total += model.score(ds) * k
            n += k
        if n == 0:
            return float("nan")
        return total / n if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """Maximize a classification metric (accuracy/f1/precision/recall)
    (ref: ClassificationScoreCalculator + Evaluation.Metric)."""

    def __init__(self, metric: str, iterator):
        self.metric = metric.lower()
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        self.iterator.reset()
        ev = model.evaluate(self.iterator)
        return float(getattr(ev, self.metric)())

    def minimize_score(self) -> bool:
        return False


class RegressionScoreCalculator(ScoreCalculator):
    """Minimize a regression metric (mse/mae/rmse) over validation data."""

    def __init__(self, metric: str, iterator):
        self.metric = metric.lower()
        self.iterator = iterator

    _METHODS = {"mse": "meanSquaredError", "mae": "meanAbsoluteError",
                "rmse": "rootMeanSquaredError"}

    def calculate_score(self, model) -> float:
        self.iterator.reset()
        ev = model.evaluateRegression(self.iterator)
        return float(getattr(ev, self._METHODS[self.metric])())


class ROCScoreCalculator(ScoreCalculator):
    """Maximize AUROC on validation data (ref: ROCScoreCalculator)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.evaluation import ROC

        roc = ROC()
        self.iterator.reset()
        for ds in self.iterator:
            out = model.output(ds.features)
            roc.eval(ds.labels, out)
        return float(roc.calculateAUC())

    def minimize_score(self) -> bool:
        return False


# ----------------------------------------------------------------------
# model savers
# ----------------------------------------------------------------------
class EarlyStoppingModelSaver:
    def save_best_model(self, model, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, model, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """Keeps deep copies in memory (ref: InMemoryModelSaver)."""

    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(model):
        # clone() shares array references, but the compiled train step
        # DONATES param buffers — continued training would delete the
        # snapshot's buffers. Materialise fresh device copies.
        if not hasattr(model, "clone"):
            return copy.deepcopy(model)
        import jax
        import jax.numpy as jnp

        snap = model.clone()
        if hasattr(model, "params_map"):       # ComputationGraph
            snap.params_map = jax.tree_util.tree_map(
                jnp.copy, model.params_map)
            snap.states_map = jax.tree_util.tree_map(
                jnp.copy, model.states_map)
        else:                                   # MultiLayerNetwork
            snap.params_list = jax.tree_util.tree_map(
                jnp.copy, model.params_list)
            snap.states_list = jax.tree_util.tree_map(
                jnp.copy, model.states_list)
        snap.opt_states = jax.tree_util.tree_map(jnp.copy, model.opt_states)
        return snap

    def save_best_model(self, model, score: float) -> None:
        self._best = self._snapshot(model)

    def save_latest_model(self, model, score: float) -> None:
        self._latest = self._snapshot(model)

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Saves bestModel.bin / latestModel.bin under a directory via
    ModelSerializer (ref: LocalFileModelSaver — same file names)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.bin")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.bin")

    def save_best_model(self, model, score: float) -> None:
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        # writeModel publishes via unique-temp + fsync + os.replace
        # (the CheckpointListener atomic pattern): bestModel.bin is
        # either the previous best or the complete new one — a crash
        # mid-save can't destroy the best model found so far
        ModelSerializer.writeModel(model, self.best_path)

    def save_latest_model(self, model, score: float) -> None:
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        ModelSerializer.writeModel(model, self.latest_path)

    def _restore(self, path):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        # restore() dispatches on the saved model_type, so graphs saved
        # by EarlyStoppingGraphTrainer come back as ComputationGraph
        return (ModelSerializer.restore(path)
                if os.path.exists(path) else None)

    def get_best_model(self):
        return self._restore(self.best_path)

    def get_latest_model(self):
        return self._restore(self.latest_path)


# ----------------------------------------------------------------------
# configuration + result + trainer
# ----------------------------------------------------------------------
@dataclass
class EarlyStoppingConfiguration:
    """Ref: EarlyStoppingConfiguration.Builder."""

    score_calculator: ScoreCalculator
    epoch_termination_conditions: Sequence[EpochTerminationCondition] = ()
    iteration_termination_conditions: Sequence[IterationTerminationCondition] = ()
    model_saver: EarlyStoppingModelSaver = field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


class TerminationReason:
    ERROR = "Error"
    ITERATION_TERMINATION = "IterationTerminationCondition"
    EPOCH_TERMINATION = "EpochTerminationCondition"


@dataclass
class EarlyStoppingResult:
    """Ref: EarlyStoppingResult — same fields."""

    termination_reason: str
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class _IterationStopListener:
    """Hooks the model's listener chain to check iteration conditions on
    every minibatch without a second loss computation."""

    def __init__(self, conditions):
        self.conditions = conditions
        self.fired: Optional[IterationTerminationCondition] = None
        self.last_score = float("nan")

    def iterationDone(self, model, iteration, epoch):
        if not self.conditions:
            # score() forces a device->host sync; don't pay it per step
            # unless an iteration condition actually needs the value
            return
        self.last_score = model.score()
        for c in self.conditions:
            if c.terminate(self.last_score):
                self.fired = c
                raise _StopIteration()

    def onEpochEnd(self, model):
        pass


class _StopIteration(Exception):
    pass


class EarlyStoppingTrainer:
    """Drives fit-one-epoch → score → maybe-save → maybe-stop
    (ref: BaseEarlyStoppingTrainer#fit)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        minimize = cfg.score_calculator.minimize_score()
        iter_listener = _IterationStopListener(
            cfg.iteration_termination_conditions)
        saved_listeners = list(getattr(self.model, "_listeners", []))
        if hasattr(self.model, "addListeners"):
            self.model.addListeners(iter_listener)
        else:
            self.model._listeners.append(iter_listener)

        score_vs_epoch: dict = {}
        best_score = float("inf") if minimize else -float("inf")
        best_epoch = -1
        last_score = best_score
        epoch = 0
        reason = TerminationReason.EPOCH_TERMINATION
        details = ""
        try:
            while True:
                try:
                    self.train_iterator.reset()
                    self.model.fit(self.train_iterator)
                except _StopIteration:
                    reason = TerminationReason.ITERATION_TERMINATION
                    details = (f"{type(iter_listener.fired).__name__} fired at"
                               f" score {iter_listener.last_score}")
                    break
                evaluated = (epoch % cfg.evaluate_every_n_epochs) == 0
                if evaluated:
                    score = cfg.score_calculator.calculate_score(self.model)
                    score_vs_epoch[epoch] = score
                    last_score = score
                    improved = (score < best_score if minimize
                                else score > best_score)
                    if improved:
                        best_score, best_epoch = score, epoch
                        cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.model, last_score)
                # score-free conditions (MaxEpochs) are checked every
                # epoch; score-based ones only when a FRESH score exists
                stop = False
                for c in cfg.epoch_termination_conditions:
                    if c.requires_score and not evaluated:
                        continue
                    if c.terminate(epoch, last_score, minimize):
                        details = f"{c!r} fired at epoch {epoch}"
                        stop = True
                        break
                epoch += 1
                if stop:
                    break
        except (KeyboardInterrupt, SystemExit):
            # interrupts/preemption must reach the caller (the
            # FaultTolerance layer turns them into a clean checkpoint-
            # and-exit). `except Exception` below never caught these
            # (they subclass BaseException), so this clause changes
            # nothing today — it makes the contract EXPLICIT so a
            # future broadening of the handler can't silently start
            # swallowing the operator's stop request; listeners are
            # still restored by the finally below
            raise
        except Exception as e:                      # noqa: BLE001
            # ref: BaseEarlyStoppingTrainer catches and reports Error
            reason = TerminationReason.ERROR
            details = f"{type(e).__name__}: {e}"
        finally:
            self.model._listeners = saved_listeners
        best_model = cfg.model_saver.get_best_model()
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=score_vs_epoch, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch,
            best_model=best_model if best_model is not None else self.model)


# ref: EarlyStoppingGraphTrainer — identical logic; ComputationGraph
# exposes the same fit/score/evaluate surface here.
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
