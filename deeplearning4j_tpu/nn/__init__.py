"""Neural network framework (reference: deeplearning4j/deeplearning4j-nn —
config system, layers, MultiLayerNetwork, ComputationGraph)."""

from deeplearning4j_tpu.nn.precision import PrecisionPolicy

__all__ = ["PrecisionPolicy"]
