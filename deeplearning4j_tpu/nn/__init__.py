"""Neural network framework (reference: deeplearning4j/deeplearning4j-nn —
config system, layers, MultiLayerNetwork, ComputationGraph)."""
