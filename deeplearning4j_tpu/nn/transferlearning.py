"""Transfer learning: fine-tune, freeze, surgery on trained networks.

Reference: org/deeplearning4j/nn/transferlearning/{TransferLearning,
FineTuneConfiguration,TransferLearningHelper} + conf/layers/misc/
FrozenLayer (SURVEY.md §2.18/§2.20 surroundings — a headline DL4J
user feature: take a zoo/imported model, freeze the feature extractor,
replace and retrain the head).

TPU notes: freezing = FrozenLayer wrapper (stop_gradient on params at
trace time, NoOp updater) — XLA then DCEs the frozen layers' backward
graph entirely, so a frozen feature extractor costs forward-only, like
the reference's workspace-level skip. TransferLearningHelper's
`featurize` precomputes frozen activations once per dataset — identical
workflow to the reference.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import IUpdater, NoOp
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork


@serializable
@dataclasses.dataclass
class FrozenLayer(Layer):
    """Wrap any layer so its params receive no gradient and no updates
    (reference: conf/layers/misc/FrozenLayer)."""

    layer: Optional[Layer] = None

    def __post_init__(self):
        # frozen params must never be updated
        self.updater = NoOp()

    @property
    def is_recurrent(self):
        return self.layer is not None and self.layer.is_recurrent

    def has_params(self):
        return self.layer.has_params()

    def output_type(self, it):
        return self.layer.output_type(it)

    def init_params(self, key, it, dtype):
        return self.layer.init_params(key, it, dtype)

    def init_state(self, it, dtype):
        return self.layer.init_state(it, dtype)

    def apply(self, params, state, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        # frozen layers run in inference mode (reference: FrozenLayer
        # disables dropout/BN-updates inside)
        return self.layer.apply(frozen, state, x, False, rng)

    def init_carry(self, batch, dtype):
        return self.layer.init_carry(batch, dtype)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply_with_carry(frozen, state, carry, x, False,
                                           rng)


@serializable
@dataclasses.dataclass
class FrozenLayerWithBackprop(FrozenLayer):
    """Frozen params, but epsilons still flow to layers below (reference:
    conf/layers/misc/FrozenLayerWithBackprop). In this functional design
    stop_gradient on params already lets the input gradient through, so
    the only difference from FrozenLayer is that the wrapped layer keeps
    its train-mode behavior (dropout/BN batch stats)."""

    def apply(self, params, state, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply(frozen, state, x, train, rng)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply_with_carry(frozen, state, carry, x, train,
                                           rng)


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied when fine-tuning (reference:
    FineTuneConfiguration.Builder — updater/lr, seed, regularization,
    dropout, activation default)."""

    updater: Optional[IUpdater] = None
    seed: Optional[int] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None


class TransferLearning:
    """Builder entry: TransferLearning.Builder(network)... (reference
    API shape preserved)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if net.params_list is None:
                raise ValueError("network must be init()ed / trained")
            self._net = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_up_to = -1          # inclusive layer index
            self._removed_from_output = 0
            self._added: List[Layer] = []
            self._nout_replace = {}          # idx -> (n_out, weight_init)

        # -- reference builder methods ---------------------------------
        def fineTuneConfiguration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def setFeatureExtractor(self, layer_idx: int):
            """Freeze layers [0, layer_idx] (reference semantics)."""
            self._freeze_up_to = int(layer_idx)
            return self

        def removeOutputLayer(self):
            return self.removeLayersFromOutput(1)

        def removeLayersFromOutput(self, n: int):
            self._removed_from_output += int(n)
            return self

        def addLayer(self, layer: Layer):
            self._added.append(layer)
            return self

        def nOutReplace(self, layer_idx: int, n_out: int,
                        weight_init: str = "xavier"):
            self._nout_replace[int(layer_idx)] = (int(n_out), weight_init)
            return self

        # -- build ------------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            src = self._net
            conf = src.conf
            n_keep = len(conf.layers) - self._removed_from_output
            if n_keep <= 0:
                raise ValueError("removed every layer")

            new_layers: List[Layer] = []
            reinit: set = set()
            for i in range(n_keep):
                layer = copy.deepcopy(conf.layers[i])
                if i in self._nout_replace:
                    n_out, wi = self._nout_replace[i]
                    layer.n_out = n_out
                    layer.weight_init = wi
                    reinit.add(i)
                    # downstream layer consumes a new width
                    if i + 1 < n_keep and hasattr(conf.layers[i + 1], "n_in"):
                        reinit.add(i + 1)
                new_layers.append(layer)
            # fix n_in of the layer after an nOutReplace
            for i, (n_out, _) in self._nout_replace.items():
                if i + 1 < n_keep and hasattr(new_layers[i + 1], "n_in"):
                    new_layers[i + 1].n_in = n_out
            for extra in self._added:
                new_layers.append(copy.deepcopy(extra))

            # freeze
            for i in range(min(self._freeze_up_to + 1, len(new_layers))):
                if new_layers[i].has_params():
                    new_layers[i] = FrozenLayer(layer=new_layers[i])

            ftc = self._ftc or FineTuneConfiguration()
            new_conf = dataclasses.replace(
                conf,
                layers=new_layers,
                seed=ftc.seed if ftc.seed is not None else conf.seed,
                updater=ftc.updater if ftc.updater is not None
                else conf.updater,
                l1=ftc.l1 if ftc.l1 is not None else conf.l1,
                l2=ftc.l2 if ftc.l2 is not None else conf.l2,
                preprocessors=dict(conf.preprocessors),
            )
            out = MultiLayerNetwork(new_conf).init()

            # copy kept params (frozen and unfrozen both keep weights;
            # reinit'd and newly-added layers keep their fresh init)
            for i in range(n_keep):
                if i in reinit:
                    continue
                out.params_list[i] = jax.tree_util.tree_map(
                    lambda a: a, src.params_list[i])
                out.states_list[i] = jax.tree_util.tree_map(
                    lambda a: a, src.states_list[i])
            return out


class TransferLearningHelper:
    """Featurize-once workflow (reference: TransferLearningHelper —
    run the frozen part once per dataset, train only the head)."""

    def __init__(self, net: MultiLayerNetwork,
                 frozen_up_to: Optional[int] = None):
        self.net = net
        if frozen_up_to is None:
            frozen_up_to = -1
            for i, l in enumerate(net.conf.layers):
                if isinstance(l, FrozenLayer):
                    frozen_up_to = i
        self.frozen_up_to = frozen_up_to
        if frozen_up_to < 0:
            raise ValueError("no frozen layers — use setFeatureExtractor "
                             "or pass frozen_up_to")
        # head-only network over the unfrozen tail
        tail_layers = [copy.deepcopy(l)
                       for l in net.conf.layers[frozen_up_to + 1:]]
        tail_pre = {i - (frozen_up_to + 1): t
                    for i, t in net.conf.preprocessors.items()
                    if i > frozen_up_to}
        tail_conf = dataclasses.replace(
            net.conf, layers=tail_layers, input_type=None,
            preprocessors=tail_pre)
        self._tail = MultiLayerNetwork.__new__(MultiLayerNetwork)
        self._tail.__init__(tail_conf)
        self._tail.init()
        for j in range(len(tail_layers)):
            self._tail.params_list[j] = net.params_list[frozen_up_to + 1 + j]
            self._tail.states_list[j] = net.states_list[frozen_up_to + 1 + j]

    def featurize(self, ds: DataSet) -> DataSet:
        """Forward through the frozen layers (reference: featurize)."""
        a = jnp.asarray(ds.features, self.net._input_dtype)
        for i in range(self.frozen_up_to + 1):
            tag = self.net.conf.preprocessors.get(i)
            if tag:
                from deeplearning4j_tpu.nn.conf.builder import (
                    apply_preprocessor,
                )
                a = apply_preprocessor(tag, a)
            a = self.net._cast_a(a, i)
            a, _ = self.net.conf.layers[i].apply(
                self.net._cast_p(self.net.params_list[i], i),
                self.net.states_list[i], a, False, None)
        return DataSet(a, ds.labels, labels_mask=ds.labels_mask)

    def fitFeaturized(self, ds: DataSet, epochs: int = 1) -> None:
        """Train the unfrozen head on featurized data, then write the
        head's params back into the full network."""
        self._tail.fit(ds.features, ds.labels, epochs=epochs)
        for j in range(len(self._tail.conf.layers)):
            self.net.params_list[self.frozen_up_to + 1 + j] = \
                self._tail.params_list[j]
            self.net.states_list[self.frozen_up_to + 1 + j] = \
                self._tail.states_list[j]

    def unfrozenMLN(self) -> MultiLayerNetwork:
        return self._tail


__all__ = ["TransferLearning", "FineTuneConfiguration", "FrozenLayer",
           "TransferLearningHelper"]


class TransferLearningGraphBuilder:
    """Transfer learning on ComputationGraph (reference:
    TransferLearning.GraphBuilder — fineTuneConfiguration,
    setFeatureExtractor(vertexName), removeVertexAndConnections,
    addLayer/addVertex, nOutReplace, setOutputs)."""

    def __init__(self, graph):
        if graph.params_map is None:
            raise ValueError("graph must be init()ed / trained")
        self._g = graph
        self._ftc: Optional[FineTuneConfiguration] = None
        self._feature_extractor: Optional[str] = None
        self._removed: set = set()
        self._added: list = []           # (name, vertex, inputs)
        self._nout_replace = {}          # name -> (n_out, weight_init)
        self._new_outputs: Optional[list] = None

    def fineTuneConfiguration(self, ftc: FineTuneConfiguration):
        self._ftc = ftc
        return self

    def setFeatureExtractor(self, vertex_name: str):
        """Freeze `vertex_name` and everything upstream of it."""
        self._feature_extractor = vertex_name
        return self

    def removeVertexAndConnections(self, name: str):
        self._removed.add(name)
        return self

    def removeVertexKeepConnections(self, name: str):
        # connections are re-declared by subsequent addLayer/addVertex
        self._removed.add(name)
        return self

    def addLayer(self, name: str, layer, *inputs):
        from deeplearning4j_tpu.nn.graph.vertices import LayerVertex
        self._added.append((name, LayerVertex(layer=layer), list(inputs)))
        return self

    def addVertex(self, name: str, vertex, *inputs):
        self._added.append((name, vertex, list(inputs)))
        return self

    def nOutReplace(self, name: str, n_out: int,
                    weight_init: str = "xavier"):
        self._nout_replace[name] = (int(n_out), weight_init)
        return self

    def setOutputs(self, *names: str):
        self._new_outputs = list(names)
        return self

    # -- build ----------------------------------------------------------
    def _ancestors(self, conf, name: str) -> set:
        """name + every node upstream of it."""
        parents = {n.name: list(n.inputs) for n in conf.nodes}
        seen = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(parents.get(cur, []))
        return seen

    def build(self):
        from deeplearning4j_tpu.nn.graph.config import GraphNode
        from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
        from deeplearning4j_tpu.nn.graph.vertices import LayerVertex

        src = self._g
        conf = src.conf
        re_added = {n for n, _, _ in self._added}
        removed = set(self._removed) - re_added
        # dropping a vertex drops everything downstream of it unless
        # re-added (reference: removeVertexAndConnections); re-added
        # names do NOT propagate removal (removeVertexKeepConnections)
        for node in conf.nodes:
            if node.name in re_added:
                continue
            if any(s in removed for s in node.inputs):
                removed.add(node.name)

        frozen: set = set()
        if self._feature_extractor is not None:
            frozen = self._ancestors(conf, self._feature_extractor)

        # nOutReplace: downstream LayerVertex consumers get the new n_in
        # and a reinit (mirrors the MLN builder above)
        consumers = {}
        for node in conf.nodes:
            for s in node.inputs:
                consumers.setdefault(s, []).append(node.name)
        reinit: set = set()
        adjust_nin = {}
        for tgt, (n_out, _) in self._nout_replace.items():
            reinit.add(tgt)
            for c in consumers.get(tgt, []):
                adjust_nin[c] = n_out
                reinit.add(c)

        added_by_name = {n: (v, i) for n, v, i in self._added}
        new_nodes = []
        placed = set()
        for node in conf.nodes:
            if node.name in re_added:
                # replaced in place: keeps the topological position
                v, i = added_by_name[node.name]
                new_nodes.append(GraphNode(name=node.name, vertex=v,
                                           inputs=i))
                placed.add(node.name)
                continue
            if node.name in removed:
                continue
            vertex = copy.deepcopy(node.vertex)
            if node.name in self._nout_replace:
                if not isinstance(vertex, LayerVertex):
                    raise ValueError(
                        f"nOutReplace target {node.name!r} is not a layer")
                n_out, wi = self._nout_replace[node.name]
                vertex.layer.n_out = n_out
                vertex.layer.weight_init = wi
            if node.name in adjust_nin and isinstance(vertex, LayerVertex) \
                    and hasattr(vertex.layer, "n_in"):
                vertex.layer.n_in = adjust_nin[node.name]
            if node.name in frozen and src.params_map.get(node.name):
                from deeplearning4j_tpu.nn.graph.vertices import FrozenVertex
                vertex = FrozenVertex(vertex=vertex)
            new_nodes.append(GraphNode(name=node.name, vertex=vertex,
                                       inputs=list(node.inputs)))
        for name, vertex, inputs in self._added:
            if name not in placed:
                new_nodes.append(GraphNode(name=name, vertex=vertex,
                                           inputs=inputs))

        ftc = self._ftc or FineTuneConfiguration()
        new_conf = dataclasses.replace(
            conf,
            nodes=new_nodes,
            network_outputs=self._new_outputs or [
                o for o in conf.network_outputs if o not in removed],
            seed=ftc.seed if ftc.seed is not None else conf.seed,
            updater=ftc.updater if ftc.updater is not None
            else conf.updater,
            l1=ftc.l1 if ftc.l1 is not None else conf.l1,
            l2=ftc.l2 if ftc.l2 is not None else conf.l2,
        )
        out = ComputationGraph(new_conf).init()
        for node in new_conf.nodes:
            name = node.name
            if name in reinit or name in re_added \
                    or name not in src.params_map:
                continue
            out.params_map[name] = jax.tree_util.tree_map(
                lambda a: a, src.params_map[name])
            out.states_map[name] = jax.tree_util.tree_map(
                lambda a: a, src.states_map[name])
        return out


TransferLearning.GraphBuilder = TransferLearningGraphBuilder
