"""Transfer learning: fine-tune, freeze, surgery on trained networks.

Reference: org/deeplearning4j/nn/transferlearning/{TransferLearning,
FineTuneConfiguration,TransferLearningHelper} + conf/layers/misc/
FrozenLayer (SURVEY.md §2.18/§2.20 surroundings — a headline DL4J
user feature: take a zoo/imported model, freeze the feature extractor,
replace and retrain the head).

TPU notes: freezing = FrozenLayer wrapper (stop_gradient on params at
trace time, NoOp updater) — XLA then DCEs the frozen layers' backward
graph entirely, so a frozen feature extractor costs forward-only, like
the reference's workspace-level skip. TransferLearningHelper's
`featurize` precomputes frozen activations once per dataset — identical
workflow to the reference.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import IUpdater, NoOp
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork


@serializable
@dataclasses.dataclass
class FrozenLayer(Layer):
    """Wrap any layer so its params receive no gradient and no updates
    (reference: conf/layers/misc/FrozenLayer)."""

    layer: Optional[Layer] = None

    def __post_init__(self):
        # frozen params must never be updated
        self.updater = NoOp()

    @property
    def is_recurrent(self):
        return self.layer is not None and self.layer.is_recurrent

    def has_params(self):
        return self.layer.has_params()

    def output_type(self, it):
        return self.layer.output_type(it)

    def init_params(self, key, it, dtype):
        return self.layer.init_params(key, it, dtype)

    def init_state(self, it, dtype):
        return self.layer.init_state(it, dtype)

    def apply(self, params, state, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        # frozen layers run in inference mode (reference: FrozenLayer
        # disables dropout/BN-updates inside)
        return self.layer.apply(frozen, state, x, False, rng)

    def init_carry(self, batch, dtype):
        return self.layer.init_carry(batch, dtype)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply_with_carry(frozen, state, carry, x, False,
                                           rng)


@serializable
@dataclasses.dataclass
class FrozenLayerWithBackprop(FrozenLayer):
    """Frozen params, but epsilons still flow to layers below (reference:
    conf/layers/misc/FrozenLayerWithBackprop). In this functional design
    stop_gradient on params already lets the input gradient through, so
    the only difference from FrozenLayer is that the wrapped layer keeps
    its train-mode behavior (dropout/BN batch stats)."""

    def apply(self, params, state, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply(frozen, state, x, train, rng)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply_with_carry(frozen, state, carry, x, train,
                                           rng)


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied when fine-tuning (reference:
    FineTuneConfiguration.Builder — updater/lr, seed, regularization,
    dropout, activation default)."""

    updater: Optional[IUpdater] = None
    seed: Optional[int] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None


class TransferLearning:
    """Builder entry: TransferLearning.Builder(network)... (reference
    API shape preserved)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if net.params_list is None:
                raise ValueError("network must be init()ed / trained")
            self._net = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_up_to = -1          # inclusive layer index
            self._removed_from_output = 0
            self._added: List[Layer] = []
            self._nout_replace = {}          # idx -> (n_out, weight_init)

        # -- reference builder methods ---------------------------------
        def fineTuneConfiguration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def setFeatureExtractor(self, layer_idx: int):
            """Freeze layers [0, layer_idx] (reference semantics)."""
            self._freeze_up_to = int(layer_idx)
            return self

        def removeOutputLayer(self):
            return self.removeLayersFromOutput(1)

        def removeLayersFromOutput(self, n: int):
            self._removed_from_output += int(n)
            return self

        def addLayer(self, layer: Layer):
            self._added.append(layer)
            return self

        def nOutReplace(self, layer_idx: int, n_out: int,
                        weight_init: str = "xavier"):
            self._nout_replace[int(layer_idx)] = (int(n_out), weight_init)
            return self

        # -- build ------------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            src = self._net
            conf = src.conf
            n_keep = len(conf.layers) - self._removed_from_output
            if n_keep <= 0:
                raise ValueError("removed every layer")

            new_layers: List[Layer] = []
            reinit: set = set()
            for i in range(n_keep):
                layer = copy.deepcopy(conf.layers[i])
                if i in self._nout_replace:
                    n_out, wi = self._nout_replace[i]
                    layer.n_out = n_out
                    layer.weight_init = wi
                    reinit.add(i)
                    # downstream layer consumes a new width
                    if i + 1 < n_keep and hasattr(conf.layers[i + 1], "n_in"):
                        reinit.add(i + 1)
                new_layers.append(layer)
            # fix n_in of the layer after an nOutReplace
            for i, (n_out, _) in self._nout_replace.items():
                if i + 1 < n_keep and hasattr(new_layers[i + 1], "n_in"):
                    new_layers[i + 1].n_in = n_out
            for extra in self._added:
                new_layers.append(copy.deepcopy(extra))

            # freeze
            for i in range(min(self._freeze_up_to + 1, len(new_layers))):
                if new_layers[i].has_params():
                    new_layers[i] = FrozenLayer(layer=new_layers[i])

            ftc = self._ftc or FineTuneConfiguration()
            new_conf = dataclasses.replace(
                conf,
                layers=new_layers,
                seed=ftc.seed if ftc.seed is not None else conf.seed,
                updater=ftc.updater if ftc.updater is not None
                else conf.updater,
                l1=ftc.l1 if ftc.l1 is not None else conf.l1,
                l2=ftc.l2 if ftc.l2 is not None else conf.l2,
                preprocessors=dict(conf.preprocessors),
            )
            out = MultiLayerNetwork(new_conf).init()

            # copy kept params (frozen and unfrozen both keep weights;
            # reinit'd and newly-added layers keep their fresh init)
            for i in range(n_keep):
                if i in reinit:
                    continue
                out.params_list[i] = jax.tree_util.tree_map(
                    lambda a: a, src.params_list[i])
                out.states_list[i] = jax.tree_util.tree_map(
                    lambda a: a, src.states_list[i])
            return out


class TransferLearningHelper:
    """Featurize-once workflow (reference: TransferLearningHelper —
    run the frozen part once per dataset, train only the head)."""

    def __init__(self, net: MultiLayerNetwork,
                 frozen_up_to: Optional[int] = None):
        self.net = net
        if frozen_up_to is None:
            frozen_up_to = -1
            for i, l in enumerate(net.conf.layers):
                if isinstance(l, FrozenLayer):
                    frozen_up_to = i
        self.frozen_up_to = frozen_up_to
        if frozen_up_to < 0:
            raise ValueError("no frozen layers — use setFeatureExtractor "
                             "or pass frozen_up_to")
        # head-only network over the unfrozen tail
        tail_layers = [copy.deepcopy(l)
                       for l in net.conf.layers[frozen_up_to + 1:]]
        tail_pre = {i - (frozen_up_to + 1): t
                    for i, t in net.conf.preprocessors.items()
                    if i > frozen_up_to}
        tail_conf = dataclasses.replace(
            net.conf, layers=tail_layers, input_type=None,
            preprocessors=tail_pre)
        self._tail = MultiLayerNetwork.__new__(MultiLayerNetwork)
        self._tail.__init__(tail_conf)
        self._tail.init()
        for j in range(len(tail_layers)):
            self._tail.params_list[j] = net.params_list[frozen_up_to + 1 + j]
            self._tail.states_list[j] = net.states_list[frozen_up_to + 1 + j]

    def featurize(self, ds: DataSet) -> DataSet:
        """Forward through the frozen layers (reference: featurize)."""
        a = jnp.asarray(ds.features, self.net._dtype)
        for i in range(self.frozen_up_to + 1):
            tag = self.net.conf.preprocessors.get(i)
            if tag:
                from deeplearning4j_tpu.nn.conf.builder import (
                    apply_preprocessor,
                )
                a = apply_preprocessor(tag, a)
            a, _ = self.net.conf.layers[i].apply(
                self.net.params_list[i], self.net.states_list[i], a,
                False, None)
        return DataSet(a, ds.labels, labels_mask=ds.labels_mask)

    def fitFeaturized(self, ds: DataSet, epochs: int = 1) -> None:
        """Train the unfrozen head on featurized data, then write the
        head's params back into the full network."""
        self._tail.fit(ds.features, ds.labels, epochs=epochs)
        for j in range(len(self._tail.conf.layers)):
            self.net.params_list[self.frozen_up_to + 1 + j] = \
                self._tail.params_list[j]
            self.net.states_list[self.frozen_up_to + 1 + j] = \
                self._tail.states_list[j]

    def unfrozenMLN(self) -> MultiLayerNetwork:
        return self._tail


__all__ = ["TransferLearning", "FineTuneConfiguration", "FrozenLayer",
           "TransferLearningHelper"]
