"""Shared features-mask validation for both network front-ends
(reference: the mask conventions of setLayerMaskArrays — SURVEY.md §5
long-context/masking)."""

from __future__ import annotations

import jax.numpy as jnp


def validate_features_mask(fm, x, ctx: str = "input"):
    """Normalize/validate a features mask against a [N,T,F] input.

    Accepts [N,T] or [N,T,1]; returns the normalized [N,T] mask.
    Anything else raises loudly — silently dropping a mask would train
    over padding.
    """
    if fm is None:
        return None
    fm = jnp.asarray(fm)
    if fm.ndim == 3 and fm.shape[-1] == 1:
        fm = fm[..., 0]
    if x.ndim != 3 or fm.ndim != 2 or fm.shape[1] != x.shape[1]:
        raise NotImplementedError(
            f"features mask shape {tuple(fm.shape)} not supported for "
            f"{ctx} of shape {tuple(x.shape)} — expected [N,T] (or "
            "[N,T,1]) matching a [N,T,F] sequence input")
    return fm
