"""Mixed-precision policy engine: fp32 master weights, reduced-precision
compute, dynamic loss scaling.

Reference gap: the reference's only precision control is the global
``Nd4j.setDefaultDataTypes`` / ``NeuralNetConfiguration.dataType`` knob
— one dtype for params, compute, updater state and losses alike. On TPU
that leaves the MXU's bf16 peak on the table (float32) or gives up
numerical protection wholesale (full bf16: params, weight updates and
reductions all downcast). The standard fix — institutionalized for GPUs
by cuDNN's compute-type/storage-type split (Chetlur et al.,
arXiv:1410.0759) and argued for weight updates specifically in Xu et
al., arXiv:2004.13336 — is a POLICY layer:

- **param_dtype** (master weights): params + updater state stay fp32;
  the weight update ``p - u`` happens in fp32 every step.
- **compute_dtype**: params are cast fp32 -> bf16/f16 ONCE per step
  inside the jitted step (the cast is part of the compiled program and
  its vjp casts gradients straight back to fp32 — master-precision
  grads for free).
- **output_dtype**: what ``output()``/``feedForward()`` hand back.
- **fp32 islands**: loss heads (softmax + reduction), normalization
  layers, and any per-layer override stay in fp32 — activations are
  cast up on entry and back down after, so reductions never accumulate
  in 8-bit mantissas.
- **dynamic loss scaling** (``mixed_float16`` only): the loss is
  multiplied by a running scale before backprop so f16 cotangents don't
  underflow; gradients are unscaled in fp32, checked for non-finites,
  and an overflowing step is SKIPPED (params/opt-state/BN-stats keep
  their old values via ``jnp.where``) while the scale halves. After
  ``growth_interval`` clean steps the scale doubles. All of it is
  jit-compatible state threaded through the compiled step.

Everything here is pure-functional and trace-friendly; the policy
object itself is a serializable dataclass that rides in the network
configuration JSON.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.profiler.telemetry import (
    LOSS_SCALE, LOSS_SCALE_OVERFLOWS, LOSS_SCALE_SKIPPED_STEPS,
    PRECISION_CASTS,
)

#: layer/vertex class names whose compute stays fp32 under mixed
#: policies (normalization statistics must not accumulate in bf16/f16)
_FP32_NORM_LAYERS = ("BatchNormalization", "LocalResponseNormalization",
                     "LayerNormalization")


@serializable
@dataclasses.dataclass
class PrecisionPolicy:
    """param/compute/output dtype triple + fp32 islands + loss scaling.

    Use the presets — ``PrecisionPolicy.of("float32")``,
    ``of("mixed_bfloat16")``, ``of("mixed_float16")`` — or construct
    directly for custom splits. ``layer_overrides`` maps a layer index
    (MultiLayerNetwork) or vertex/layer name (ComputationGraph) to a
    dtype string, overriding the policy's compute dtype for that layer
    (e.g. force one attention block to fp32)."""

    name: str = "float32"
    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    output_dtype: str = "float32"
    #: layer class names forced to fp32 compute (normalization et al.)
    fp32_layer_types: tuple = _FP32_NORM_LAYERS
    #: loss heads (softmax + loss reduction) compute in fp32
    fp32_loss_head: bool = True
    #: {layer index | layer/vertex name: dtype string} per-layer forcing
    layer_overrides: dict = dataclasses.field(default_factory=dict)
    # -- dynamic loss scaling (mixed_float16) ---------------------------
    loss_scaling: bool = False
    initial_loss_scale: float = 2.0 ** 15
    loss_scale_growth: float = 2.0
    loss_scale_backoff: float = 0.5
    #: consecutive finite-grad steps before the scale grows
    growth_interval: int = 200
    min_loss_scale: float = 1.0
    #: growth ceiling: a run whose f16 path never overflows (e.g. all
    #: hot layers overridden to fp32) would otherwise double the scale
    #: to f32 inf in ~23k steps — and inf * backoff = inf can never
    #: recover, silently skipping every step thereafter
    max_loss_scale: float = 2.0 ** 24

    def __post_init__(self):
        # JSON round-trip: tuples come back as lists, int keys as strings
        if isinstance(self.fp32_layer_types, list):
            self.fp32_layer_types = tuple(self.fp32_layer_types)
        if self.layer_overrides:
            self.layer_overrides = {
                (int(k) if str(k).lstrip("-").isdigit() else k): v
                for k, v in self.layer_overrides.items()}

    # ------------------------------------------------------------------
    @staticmethod
    def of(name: str) -> "PrecisionPolicy":
        """Resolve a preset name ("float32" / "mixed_bfloat16" /
        "mixed_float16", plus dtype aliases like "mixed_bf16")."""
        from deeplearning4j_tpu.ndarray.dtypes import DataType

        key = str(name).strip().lower()
        if key in ("float32", "f32", "fp32"):
            return PrecisionPolicy(name="float32")
        if key.startswith("mixed_"):
            dt = DataType.from_any(key[len("mixed_"):])
            if dt is DataType.BFLOAT16:
                return PrecisionPolicy(name="mixed_bfloat16",
                                       compute_dtype="bfloat16")
            if dt is DataType.HALF:
                return PrecisionPolicy(name="mixed_float16",
                                       compute_dtype="float16",
                                       loss_scaling=True)
        raise ValueError(
            f"Unknown precision policy {name!r} (expected 'float32', "
            "'mixed_bfloat16', 'mixed_float16', or a PrecisionPolicy)")

    @staticmethod
    def identity(dtype: str) -> "PrecisionPolicy":
        """Single-dtype policy matching the legacy conf.dtype behavior
        (params == compute == output; no fp32 islands, no scaling) —
        resolves to a strict no-op in the network code paths."""
        return PrecisionPolicy(name=f"identity:{dtype}",
                               param_dtype=dtype, compute_dtype=dtype,
                               output_dtype=dtype, fp32_layer_types=(),
                               fp32_loss_head=False)

    @staticmethod
    def resolve(precision, conf_dtype: str) -> "PrecisionPolicy":
        """Conf seam: ``precision`` is None (legacy single-dtype mode
        driven by conf.dtype), a preset name, or a PrecisionPolicy."""
        if precision is None:
            return PrecisionPolicy.identity(conf_dtype)
        if isinstance(precision, PrecisionPolicy):
            return precision
        return PrecisionPolicy.of(precision)

    # ------------------------------------------------------------------
    @property
    def is_identity(self) -> bool:
        """True when no cast/scaling machinery is needed — networks keep
        their exact single-dtype code paths (and donation patterns).
        A uniform LOW-precision policy with fp32 islands configured is
        NOT identity: the islands require the cast machinery (a
        directly-constructed all-bf16 policy keeps the default
        fp32_loss_head protection unless explicitly cleared)."""
        if (self.param_dtype != self.compute_dtype
                or self.compute_dtype != self.output_dtype
                or self.loss_scaling or self.layer_overrides):
            return False
        # uniform fp32: islands are vacuous; uniform low precision:
        # identity only if the islands were explicitly turned off
        return (self.compute_dtype == "float32"
                or (not self.fp32_loss_head
                    and not self.fp32_layer_types))

    def layer_compute_dtype(self, layer, key) -> jnp.dtype:
        """Resolved compute dtype for one layer. ``key`` is the layer
        index (MLN) or vertex name (CG); matched against
        ``layer_overrides`` first (also by ``layer.name``), then the
        fp32 forcing rules, then the policy compute dtype."""
        ov = self.layer_overrides
        if ov:
            if key in ov:
                return jnp.dtype(ov[key])
            lname = getattr(layer, "name", None)
            if lname is not None and lname in ov:
                return jnp.dtype(ov[lname])
        if layer is not None:
            if self.fp32_loss_head and hasattr(layer, "loss_value"):
                return jnp.dtype("float32")
            if type(layer).__name__ in self.fp32_layer_types:
                return jnp.dtype("float32")
        return jnp.dtype(self.compute_dtype)


# ---------------------------------------------------------------- casts
def cast_leaf(a, dtype):
    """Cast one floating array; non-float leaves (int masks, counters)
    pass through untouched."""
    if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
            and a.dtype != dtype:
        return a.astype(dtype)
    return a


def cast_tree(tree, dtype):
    """Cast every floating leaf of a pytree (one layer's params)."""
    return jax.tree_util.tree_map(lambda a: cast_leaf(a, dtype), tree)


def count_casts(params_tree, dtype) -> int:
    """Leaves that WILL be cast per step for a given compute dtype —
    the static cast-count telemetry gauge."""
    n = 0
    for a in jax.tree_util.tree_leaves(params_tree):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != dtype:
            n += 1
    return n


# ----------------------------------------------------- loss-scale state
def init_loss_scale(policy: PrecisionPolicy) -> Optional[Dict[str, Any]]:
    """Fresh jit-compatible loss-scale state, or None when the policy
    doesn't scale. Counters ride in the state so they survive jit
    donation and checkpoints."""
    if not policy.loss_scaling:
        return None
    # overflows == skipped_steps in the current engine (every detected
    # overflow skips exactly one step); they are kept as separate
    # counters because the NAMES are the telemetry contract and a
    # future partial-skip path (e.g. gradient accumulation skipping
    # only the flush) would diverge them without a metric rename
    return {
        "scale": jnp.asarray(policy.initial_loss_scale, jnp.float32),
        "good_steps": jnp.asarray(0, jnp.int32),
        "overflows": jnp.asarray(0, jnp.int32),
        "skipped_steps": jnp.asarray(0, jnp.int32),
    }


def scale_loss(loss, ls_state):
    return loss * ls_state["scale"].astype(loss.dtype)


def unscale_grads(grads, ls_state):
    """Divide gradients by the live scale, in fp32 (master grads)."""
    inv = 1.0 / ls_state["scale"]

    def one(g):
        g = g.astype(jnp.promote_types(g.dtype, jnp.float32))
        return g * inv.astype(g.dtype)

    return jax.tree_util.tree_map(one, grads)


def all_finite(tree):
    """Scalar bool: every element of every leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    flags = [jnp.all(jnp.isfinite(l)) for l in leaves
             if jnp.issubdtype(jnp.result_type(l), jnp.floating)]
    if not flags:
        return jnp.asarray(True)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def select(pred, new_tree, old_tree):
    """Per-leaf ``where(pred, new, old)`` — the skip-step primitive."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(pred, n, o), new_tree, old_tree)


def update_loss_scale(policy: PrecisionPolicy, ls_state, finite):
    """Dynamic loss-scale schedule: overflow -> halve (floored at
    min_loss_scale) and reset the streak; ``growth_interval`` clean
    steps -> double (capped at max_loss_scale). Counters accumulate on
    device."""
    scale = ls_state["scale"]
    good = ls_state["good_steps"]
    interval = jnp.asarray(policy.growth_interval, jnp.int32)
    grown = jnp.where(good + 1 >= interval,
                      jnp.minimum(scale * policy.loss_scale_growth,
                                  policy.max_loss_scale), scale)
    shrunk = jnp.maximum(scale * policy.loss_scale_backoff,
                         policy.min_loss_scale)
    overflow = jnp.logical_not(finite).astype(jnp.int32)
    return {
        "scale": jnp.where(finite, grown, shrunk),
        "good_steps": jnp.where(
            finite, jnp.where(good + 1 >= interval, 0, good + 1), 0
        ).astype(jnp.int32),
        "overflows": ls_state["overflows"] + overflow,
        "skipped_steps": ls_state["skipped_steps"] + overflow,
    }


def scaled_value_and_grad(loss_fn, ls_state, params):
    """The loss-scaling forward/backward scaffold shared by every step
    builder: differentiate ``scale * loss_fn(params)``, unscale the
    gradients in fp32, and judge finiteness BEFORE any clipping (an
    elementwise clip would truncate an inf to the threshold and mask
    the overflow). ``loss_fn`` returns ``(loss, aux)``; returns
    ``((loss, aux), unscaled_grads, finite)``."""

    def wrapped(p):
        loss, aux = loss_fn(p)
        return scale_loss(loss, ls_state), aux

    out, grads = jax.value_and_grad(wrapped, has_aux=True)(params)
    grads = unscale_grads(grads, ls_state)
    return out, grads, all_finite(grads)


def guard_scaled_step(policy: PrecisionPolicy, ls_state, finite,
                      new_old_pairs):
    """The skip-step tail shared by every step builder: on a non-finite
    step each (new, old) tree pair resolves to OLD (params, optimizer
    moments, BN stats all held), and the loss-scale state advances per
    the schedule. Returns (guarded trees..., new_ls_state)."""
    guarded = tuple(select(finite, n, o) for n, o in new_old_pairs)
    return guarded + (update_loss_scale(policy, ls_state, finite),)


# ------------------------------------- int8 weight-only PTQ (serving)
# Post-training quantization preset for DECODE serving: autoregressive
# decode is HBM-bandwidth-bound (every step re-reads every weight for
# one token per slot), so storing weights as int8 with per-channel fp32
# scales cuts the bytes/step ~4x vs fp32 (~2x vs bf16) while the
# matmul itself dequantizes on the fly — ``(x @ q) * scale`` — and
# accumulates in the compute dtype. Weight-only: activations, KV cache,
# norms and biases keep their float dtype, so there is no activation
# calibration step. Symmetric per-channel scales (one fp32 scale per
# output channel, ``axis`` selects which dimension is "channels") keep
# the worst-case quantization error per channel bounded by half an
# int8 ulp of that channel's max.
#
# Consumed by serving/engine.py's ``quantization="int8"`` decode path;
# usable standalone on any 2-D weight tree.

def quantize_int8(w, axis: int = -1) -> Dict[str, Any]:
    """Symmetric per-channel int8 quantization of one weight array.

    ``axis`` is the preserved (channel) axis: the returned ``scale``
    has shape ``(w.shape[axis],)`` and ``w ≈ q * scale`` broadcast
    along ``axis``. All-zero channels get scale 1 (q is then 0)."""
    w = jnp.asarray(w)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    bshape = [1] * w.ndim
    bshape[axis] = -1
    q = jnp.clip(jnp.round(w.astype(jnp.float32)
                           / scale.reshape(bshape)),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale, "axis": axis}


def is_int8(leaf) -> bool:
    """True for a ``quantize_int8`` result dict."""
    return (isinstance(leaf, dict) and "q" in leaf and "s" in leaf
            and getattr(leaf["q"], "dtype", None) == jnp.int8)


def dequantize_int8(wq: Dict[str, Any], dtype=jnp.float32):
    """Materialize the full-precision approximation ``q * scale``."""
    q, s, axis = wq["q"], wq["s"], int(wq.get("axis", -1)) % wq["q"].ndim
    bshape = [1] * q.ndim
    bshape[axis] = -1
    return q.astype(dtype) * s.reshape(bshape).astype(dtype)


def int8_matmul(x, w, dtype):
    """Dequant-in-matmul for a weight quantized along its OUTPUT axis
    (``axis=1`` of a [in, out] matrix): ``(x @ q) * scale``. Plain
    arrays pass through as ``x @ w.astype(dtype)`` so call sites stay
    quantization-agnostic. The int8 tensor is upcast lane-wise inside
    the fused matmul — HBM traffic stays int8."""
    if is_int8(w):
        return (x @ w["q"].astype(dtype)) * w["s"].astype(dtype)
    return x @ w.astype(dtype)


def quantized_bytes(tree) -> int:
    """Weight bytes of a (possibly partially) quantized tree — the
    number the ``int8`` preset exists to shrink."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total


# --------------------------------------- fp8 KV-cache quantization
# The KV-cache companion to the int8 weight preset above: decode
# re-reads the whole paged KV cache every step, so storing K/V pages
# as float8_e4m3fn with per-page-per-head fp32 scale planes halves the
# page bytes vs bf16 — half the HBM traffic per decode step AND ~2x
# the effective KV capacity from the same pool (which the prefix cache
# and sticky sessions multiply again). Symmetric absmax scaling, same
# shape-preserving contract as the int8 helpers: ``x ≈ q * scale``.
#
# e4m3fn specifics that the helpers encode so call sites can't get
# them wrong: the format's max finite value is 448 and values beyond
# it cast to NaN (no inf encoding), so quantization CLIPS to ±448
# before the cast; the scale is floored so an all-zero page still
# divides/multiplies cleanly (and fresh scale planes initialize to
# ONES, matching the zero-initialized pools: 0 * 1 == 0).
#
# Consumed by serving/kv_pages.py (``kv_dtype="fp8_e4m3"``) and
# dequantized either in the paged-attention Pallas kernel (one scalar
# multiply per VMEM page block) or in its XLA reference path.

FP8_E4M3_MAX = 448.0
#: scale floor: amax/448 for any amax below 1.0/448 would round-trip
#: tiny pages through denormal scales; 1/448 keeps scale*448 >= 1
FP8_SCALE_FLOOR = 1.0 / 448.0

_KV_DTYPE_ALIASES = {
    "fp8_e4m3": "fp8_e4m3", "fp8": "fp8_e4m3", "e4m3": "fp8_e4m3",
    "float8_e4m3fn": "fp8_e4m3", "float8_e4m3": "fp8_e4m3",
}


def resolve_kv_dtype(kv_dtype) -> Optional[str]:
    """Canonicalize a KV-cache quantization request: None / "" stay
    None (pool keeps the compute dtype); "fp8"/"e4m3"/"float8_e4m3fn"
    and friends resolve to the canonical ``"fp8_e4m3"``. Raises on
    unknown names and on jax builds without the fp8 dtype."""
    if kv_dtype is None or kv_dtype == "":
        return None
    key = str(kv_dtype).strip().lower()
    if key in ("none", "bf16", "bfloat16", "native"):
        return None
    canon = _KV_DTYPE_ALIASES.get(key)
    if canon is None:
        raise ValueError(
            f"Unknown kv_dtype {kv_dtype!r} (expected None or one of "
            f"{sorted(set(_KV_DTYPE_ALIASES))})")
    if fp8_kv_dtype() is None:
        raise ValueError(
            "kv_dtype='fp8_e4m3' requires a jax with float8_e4m3fn")
    return canon


def fp8_kv_dtype():
    """The storage dtype behind ``kv_dtype="fp8_e4m3"`` (None when
    this jax build predates float8)."""
    return getattr(jnp, "float8_e4m3fn", None)


def fp8_scale(amax):
    """Per-page-per-head scale from an abs-max: ``max(amax/448,
    floor)``, fp32. Shape-preserving."""
    return jnp.maximum(
        jnp.asarray(amax, jnp.float32) / FP8_E4M3_MAX, FP8_SCALE_FLOOR)


def quantize_fp8(x, scale):
    """``clip(x / scale, ±448)`` cast to float8_e4m3fn; ``scale``
    must broadcast against ``x``. The clip is load-bearing: e4m3fn
    has no inf, out-of-range casts produce NaN."""
    xf = x.astype(jnp.float32) / jnp.asarray(scale, jnp.float32)
    return jnp.clip(xf, -FP8_E4M3_MAX, FP8_E4M3_MAX).astype(
        fp8_kv_dtype())


def dequantize_fp8(q, scale, dtype=jnp.float32):
    """``q * scale`` in fp32, cast to ``dtype``."""
    return (q.astype(jnp.float32)
            * jnp.asarray(scale, jnp.float32)).astype(dtype)


# ------------------------------------------------------------ telemetry
def record_cast_count(site: str, n: int) -> None:
    """Static per-step cast count gauge (set at step-build time)."""
    from deeplearning4j_tpu.profiler import telemetry

    if not telemetry.enabled():
        return
    telemetry.MetricsRegistry.get_default().gauge(
        PRECISION_CASTS,
        "param leaves cast param_dtype->compute_dtype per compiled step"
    ).set(n, site=site)


def record_loss_scale(site: str, ls_state,
                      seen: Tuple[int, int]) -> Tuple[int, int]:
    """Mirror the device-side loss-scale state into telemetry: the
    ``loss_scale`` gauge plus DELTA increments of the overflow/skip
    counters since ``seen``. Forces one device->host sync — only called
    on mixed_float16 steps, and documented as such; returns the new
    ``seen`` tuple."""
    from deeplearning4j_tpu.profiler import telemetry

    if not telemetry.enabled() or ls_state is None:
        return seen
    scale, of, sk = jax.device_get(
        [ls_state["scale"], ls_state["overflows"],
         ls_state["skipped_steps"]])
    scale, of, sk = float(scale), int(of), int(sk)
    reg = telemetry.MetricsRegistry.get_default()
    reg.gauge(LOSS_SCALE, "current dynamic loss scale").set(
        scale, site=site)
    if of > seen[0]:
        reg.counter(LOSS_SCALE_OVERFLOWS,
                    "gradient overflows detected (non-finite grads)"
                    ).inc(of - seen[0], site=site)
    if sk > seen[1]:
        reg.counter(LOSS_SCALE_SKIPPED_STEPS,
                    "training steps skipped (params held) on overflow"
                    ).inc(sk - seen[1], site=site)
    return (of, sk)


def loss_scale_context(ls_state) -> str:
    """Human-readable loss-scale summary for NaN-panic messages (the
    panic path already syncs, so the extra fetch is free)."""
    if ls_state is None:
        return ""
    scale, of, sk = jax.device_get(
        [ls_state["scale"], ls_state["overflows"],
         ls_state["skipped_steps"]])
    return (f" [loss_scale={float(scale):g} overflows={int(of)} "
            f"skipped_steps={int(sk)}; a non-finite LOSS on an "
            "overflow step is expected — the step was skipped and the "
            "scale halved]")


__all__ = [
    "PrecisionPolicy", "cast_leaf", "cast_tree", "count_casts",
    "init_loss_scale", "scale_loss", "unscale_grads", "all_finite",
    "select", "update_loss_scale", "scaled_value_and_grad",
    "guard_scaled_step",
    "quantize_int8", "dequantize_int8", "int8_matmul", "is_int8",
    "quantized_bytes",
    "FP8_E4M3_MAX", "FP8_SCALE_FLOOR", "resolve_kv_dtype",
    "fp8_kv_dtype", "fp8_scale", "quantize_fp8", "dequantize_fp8",
    "record_cast_count",
    "record_loss_scale", "loss_scale_context",
    "LOSS_SCALE", "LOSS_SCALE_OVERFLOWS", "LOSS_SCALE_SKIPPED_STEPS",
    "PRECISION_CASTS",
]
