"""MultiLayerNetwork — sequential network front-end.

Reference: org/deeplearning4j/nn/multilayer/MultiLayerNetwork.java
(~4k LoC) + the training driver stack (Solver, BaseOptimizer,
StochasticGradientDescent, MultiLayerUpdater — SURVEY.md §2.19, §2.22,
§3.1).

The reference's fit() runs a per-layer, per-op eager loop crossing JNI
thousands of times per iteration, with params/gradients living in flat
mutable view arrays. The TPU-native design compiles the ENTIRE training
iteration — forward, loss, backward, updater, param update — into ONE
XLA executable (`jax.jit` with donated buffers), executed per minibatch.
That single design decision replaces: LayerWorkspaceMgr arenas (XLA
buffer assignment), the updater loop (fused into the step), gradient
views (pytree + donation), and the flow-controller sync machinery
(XLA's dataflow schedule).

Parity surface kept from the reference: init()/fit()/output()/score()/
params()/setParams()/numParams()/evaluate()/summary(), listener
callbacks, per-layer updater overrides (incl. NoOp freezing),
l1/l2 regularization, gradient clipping modes.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.learning.schedules import ISchedule, ScheduleType
from deeplearning4j_tpu.learning.updaters import IUpdater, apply_updater
from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import model_health as _model_health
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.profiler import tracing as _tracing


def _eval_mask(ds):
    """Label mask for evaluation, with the evalTimeSeries convention:
    per-timestep labels + a features mask and no explicit label mask
    means the features mask IS the label mask (reference: RNN masking)."""
    if ds.labels_mask is None and ds.features_mask is not None \
            and np.asarray(ds.labels).ndim == 3:
        return ds.features_mask
    return ds.labels_mask


def _uses_epoch_schedule(upd) -> bool:
    """True if the updater's LR schedule counts epochs, not iterations
    (reference: ScheduleType.EPOCH resolved in BaseMultiLayerUpdater)."""
    lr = getattr(upd, "learning_rate", None)
    return isinstance(lr, ISchedule) and lr.schedule_type is ScheduleType.EPOCH
from deeplearning4j_tpu.ndarray.dtypes import DataType
from deeplearning4j_tpu.ndarray.ndarray import NDArray, _unwrap
from deeplearning4j_tpu.nn import precision as _precision
from deeplearning4j_tpu.nn.conf.builder import (
    MultiLayerConfiguration, apply_preprocessor,
)
from deeplearning4j_tpu.nn.conf.constraint import apply_constraints
from deeplearning4j_tpu.nn.conf.layers import LossLayer, OutputLayer

#: param keys subject to l1/l2 (weights, not biases/scales — reference
#: regularizes weights by default, bias via separate l2Bias we omit)
_REGULARIZED_KEYS = {"W", "RW", "dW", "pW", "Wq", "Wk", "Wv", "Wo"}


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params_list: Optional[List[dict]] = None   # per-layer param dicts
        self.states_list: Optional[List[dict]] = None   # per-layer non-trainable state
        self.opt_states: Optional[List[Any]] = None     # per-layer updater state
        self._updaters: List[IUpdater] = []
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self._listeners: List[Any] = []
        self._rng_key = None
        self._step_cache: dict = {}
        self._fwd_cache: dict = {}
        self._pretrain_cache: dict = {}
        self._rnn_carries = None    # stateful rnnTimeStep hidden state
        self._rnn_batch = 0
        # mixed-precision policy (nn/precision.py): identity policies
        # (precision=None / single-dtype) keep the legacy code paths
        # bit-for-bit; mixed policies split param vs compute dtype
        self._policy = _precision.PrecisionPolicy.resolve(
            getattr(conf, "precision", None), conf.dtype)
        self._mixed = not self._policy.is_identity
        #: MASTER param dtype (fp32 under mixed policies)
        self._dtype = DataType.from_any(self._policy.param_dtype).jax
        #: dtype inputs are staged in (compute dtype — halves transfer
        #: bytes under mixed policies)
        self._input_dtype = DataType.from_any(
            self._policy.compute_dtype).jax
        #: dtype output()/feedForward() return
        self._out_dtype = DataType.from_any(
            self._policy.output_dtype).jax
        self._compute_dtypes: List[Any] = []
        self._loss_scale_state = None
        self._ls_seen = (0, 0)
        # in-step model-health monitor (profiler/model_health.py);
        # None keeps every step builder on its legacy code path
        self._health = None

    # ------------------------------------------------------------------
    # initialization (reference: MultiLayerNetwork#init + ParamInitializer)
    # ------------------------------------------------------------------
    def init(self) -> "MultiLayerNetwork":
        conf = self.conf
        key = jax.random.key(conf.seed)
        it = conf.input_type
        if it is None:
            # manual-n_in path (reference allows omitting setInputType when
            # every layer's nIn is explicit); derive the input type from
            # the first parameterized layer
            it = self._infer_input_type()
        self.params_list, self.states_list, self._updaters = [], [], []
        self.opt_states = []
        for i, layer in enumerate(conf.layers):
            tag = conf.preprocessors.get(i)
            if tag == "flatten":
                from deeplearning4j_tpu.nn.conf.inputs import InputType
                it = InputType.feedForward(it.flat_size())
            elif tag and tag.startswith("to_conv:"):
                from deeplearning4j_tpu.nn.conf.inputs import InputType
                h, w, c = (int(v) for v in tag.split(":", 1)[1].split(","))
                it = InputType.convolutional(h, w, c)
            elif it.kind == "convolutionalFlat":
                from deeplearning4j_tpu.nn.conf.inputs import InputType
                it = InputType.feedForward(it.flat_size())
            key, sub = jax.random.split(key)
            p = layer.init_params(sub, it, self._dtype)
            s = layer.init_state(it, self._dtype)
            upd = layer.updater if layer.updater is not None else conf.updater
            self.params_list.append(p)
            self.states_list.append(s)
            self._updaters.append(upd)
            self.opt_states.append(upd.init_state(p))
            it = layer.output_type(it)
        self._output_type = it
        self._rng_key = jax.random.key(conf.seed ^ 0x5EED)
        # per-layer compute dtypes (fp32 islands for loss heads /
        # normalization stay fp32 under mixed policies)
        self._compute_dtypes = [
            self._policy.layer_compute_dtype(l, i)
            for i, l in enumerate(conf.layers)]
        self._loss_scale_state = _precision.init_loss_scale(self._policy)
        self._ls_seen = (0, 0)
        if self._mixed:
            _precision.record_cast_count("mln", sum(
                _precision.count_casts(p, self._compute_dtypes[i])
                for i, p in enumerate(self.params_list)))
        return self

    def _infer_input_type(self):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer, LSTM, SimpleRnn, SubsamplingLayer,
        )

        first = self.conf.layers[0]
        if isinstance(first, (ConvolutionLayer, SubsamplingLayer)):
            raise ValueError(
                "Image networks need setInputType(InputType.convolutional"
                "(h, w, c)) — channel count alone does not fix the geometry")
        n_in = getattr(first, "n_in", 0)
        if not n_in:
            raise ValueError(
                "Without setInputType, the first layer must declare n_in")
        if isinstance(first, (LSTM, SimpleRnn)):
            return InputType.recurrent(n_in)
        return InputType.feedForward(n_in)

    def _check_init(self):
        if self.params_list is None:
            raise RuntimeError("Call init() first")

    # ------------------------------------------------------------------
    # mixed-precision seams (identity policies: strict no-ops)
    # ------------------------------------------------------------------
    def _cd(self, i):
        """Compute dtype of layer i under the active policy."""
        return self._compute_dtypes[i] if self._mixed else self._dtype

    def _cast_p(self, p, i):
        """Cast one layer's MASTER params to its compute dtype. Inside
        the jitted step this happens once per step, and its vjp casts
        the gradients straight back to the master dtype (fp32)."""
        return _precision.cast_tree(p, self._compute_dtypes[i]) \
            if self._mixed else p

    def _cast_a(self, a, i):
        """Cast the activation entering layer i (fp32 islands cast up,
        and back down at the next reduced-precision layer)."""
        return _precision.cast_leaf(a, self._compute_dtypes[i]) \
            if self._mixed else a

    # ------------------------------------------------------------------
    # forward (reference: feedForward / ffToLayerActivationsInWs)
    # ------------------------------------------------------------------
    def _forward(self, params_list, states_list, x, train: bool, rng,
                 fmask=None):
        """Pure forward through all layers. Returns (out, new_states)."""
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer

        conf = self.conf
        a = x
        if fmask is not None:
            a = a * fmask[..., None].astype(a.dtype)
        new_states = []
        keys = (jax.random.split(rng, len(conf.layers))
                if rng is not None else [None] * len(conf.layers))
        for i, layer in enumerate(conf.layers):
            tag = conf.preprocessors.get(i)
            if tag:
                a = apply_preprocessor(tag, a)
            a = self._cast_a(a, i)
            p_i = self._cast_p(params_list[i], i)
            if fmask is not None and isinstance(layer, GlobalPoolingLayer) \
                    and a.ndim == 3 and a.shape[1] == fmask.shape[1]:
                a, ns = layer.apply_masked(p_i, states_list[i],
                                           a, fmask, train, keys[i])
            else:
                a, ns = layer.apply(p_i, states_list[i], a,
                                    train, keys[i])
            new_states.append(ns)
        if self._mixed:
            a = _precision.cast_leaf(a, self._out_dtype)
        return a, new_states

    def _loss(self, params_list, states_list, x, y, mask, rng, fmask=None,
              collect_acts=False):
        """Forward to the loss head; fused stable loss on pre-activations.
        ``collect_acts=True`` (the HealthMonitor step path) extends the
        aux with per-layer non-finite activation flags."""
        loss, (new_states, data_loss, _, act_bad) = self._loss_carries(
            params_list, states_list, None, x, y, mask, rng, fmask,
            collect_acts=collect_acts)
        if collect_acts:
            return loss, (new_states, data_loss, act_bad)
        return loss, (new_states, data_loss)

    def _loss_carries(self, params_list, states_list, carries, x, y, mask,
                      rng, fmask=None, collect_acts=False):
        """Loss forward threading recurrent hidden state (tBPTT path:
        reference MultiLayerNetwork#doTruncatedBPTT keeps each layer's
        rnnTimeStep state across segments; gradient truncation falls out
        of the carries entering the jitted segment step as inputs)."""
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer

        conf = self.conf
        a = x
        # features mask: zero padded timesteps at the input (reference:
        # setLayerMaskArrays; padded inputs contribute nothing) — masked
        # pooling below handles the reduction side
        if fmask is not None:
            a = a * fmask[..., None].astype(a.dtype)
        new_states = []
        new_carries = []
        # per-layer non-finite forward flags (model-health provenance)
        act_bad = [] if collect_acts else None
        keys = (jax.random.split(rng, len(conf.layers))
                if rng is not None else [None] * len(conf.layers))
        for i, layer in enumerate(conf.layers[:-1]):
            tag = conf.preprocessors.get(i)
            if tag:
                a = apply_preprocessor(tag, a)
            a = self._cast_a(a, i)
            p_i = self._cast_p(params_list[i], i)
            k_i = keys[i]
            # masked global pooling when the time axis still lines up
            if fmask is not None and isinstance(layer, GlobalPoolingLayer) \
                    and a.ndim == 3 and a.shape[1] == fmask.shape[1]:
                a, ns = layer.apply_masked(p_i, states_list[i], a, fmask,
                                           True, k_i)
                new_states.append(ns)
                new_carries.append(None)
                if collect_acts:
                    act_bad.append(_model_health.act_flag(a))
                continue
            # weight noise (reference: IWeightNoise applied per training
            # forward; DropConnect/WeightNoise in conf/weightnoise)
            if getattr(layer, "weight_noise", None) is not None \
                    and k_i is not None:
                k_i, k_wn = jax.random.split(k_i)
                p_i = layer.weight_noise.apply(p_i, k_wn)
            if carries is not None and layer.is_recurrent:
                a, ns, c = layer.apply_with_carry(
                    p_i, states_list[i], carries[i], a, True, k_i)
            else:
                a, ns = layer.apply(p_i, states_list[i], a, True, k_i)
                c = None
            new_states.append(ns)
            new_carries.append(c)
            if collect_acts:
                act_bad.append(_model_health.act_flag(a))
        new_carries.append(None)  # loss head is never recurrent
        last = conf.layers[-1]
        if not hasattr(last, "loss_value"):
            raise ValueError("Last layer must be an OutputLayer/LossLayer "
                             "(or another loss-bearing head, e.g. "
                             "OCNNOutputLayer) to fit()")
        tag = conf.preprocessors.get(len(conf.layers) - 1)
        if tag:
            a = apply_preprocessor(tag, a)
        # loss head: fp32 island under mixed policies — the activation
        # is cast UP so the logits, softmax and loss reduction all run
        # at full precision (the policy's fp32_loss_head default)
        a = self._cast_a(a, len(conf.layers) - 1)
        p_last = self._cast_p(params_list[-1], len(conf.layers) - 1)
        if getattr(last, "weight_noise", None) is not None \
                and keys[-1] is not None:
            p_last = last.weight_noise.apply(p_last, keys[-1])
        data_loss = last.loss_value(p_last, states_list[-1], a, y, mask)
        new_states.append(states_list[-1])
        if collect_acts:
            # the loss head's provenance bit is its loss value: a clean
            # prefix + non-finite loss localizes the blow-up to the head
            act_bad.append(_model_health.act_flag(data_loss))

        # l1/l2 regularization (reference: BaseLayer#calcRegularizationScore)
        reg = jnp.asarray(0.0, data_loss.dtype)
        for layer, p in zip(conf.layers, params_list):
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for k, v in p.items():
                if k in _REGULARIZED_KEYS:
                    if l1:
                        reg = reg + l1 * jnp.sum(jnp.abs(v))
                    if l2:
                        reg = reg + 0.5 * l2 * jnp.sum(v * v)
        return data_loss + reg, (new_states, data_loss, new_carries,
                                 act_bad)

    def _clip_grads(self, grads_list):
        mode = self.conf.gradient_normalization
        if not mode:
            return grads_list
        t = self.conf.gradient_normalization_threshold
        if mode == "ClipElementWiseAbsoluteValue":
            return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), grads_list)
        if mode == "ClipL2PerLayer":
            out = []
            for g in grads_list:
                leaves = jax.tree_util.tree_leaves(g)
                norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-12)
                scale = jnp.minimum(1.0, t / norm)
                out.append(jax.tree_util.tree_map(lambda l: l * scale, g))
            return out
        if mode == "RenormalizeL2PerLayer":
            out = []
            for g in grads_list:
                leaves = jax.tree_util.tree_leaves(g)
                norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-12)
                out.append(jax.tree_util.tree_map(lambda l: l / norm, g))
            return out
        raise ValueError(f"Unknown gradient normalization: {mode}")

    # ------------------------------------------------------------------
    # the compiled training step
    # ------------------------------------------------------------------
    def _apply_updates(self, params_list, opt_states, grads, it_step,
                       ep_step):
        """Master-precision weight update: grads arrive fp32 (the
        param-cast vjp), apply_updater keeps the math fp32, and
        ``p - u`` runs in the master dtype."""
        new_params, new_opt = [], []
        for i in range(len(params_list)):
            step = ep_step if _uses_epoch_schedule(self._updaters[i]) else it_step
            updates, no = apply_updater(self._updaters[i], opt_states[i],
                                        grads[i], params_list[i], step)
            np_i = jax.tree_util.tree_map(
                lambda p, u: p - u, params_list[i], updates)
            # post-update constraints (reference: BaseConstraint)
            new_params.append(apply_constraints(self.conf.layers[i], np_i))
            new_opt.append(no)
        return new_params, new_opt

    def _get_train_step(self, has_mask: bool, has_fmask: bool = False) -> Callable:
        # the health flag is STATIC: toggling a HealthMonitor on/off
        # costs exactly one extra compile per site, nothing per step
        health = self._health is not None
        key = (has_mask, has_fmask, health)
        if key in self._step_cache:
            return self._step_cache[key]
        policy = self._policy
        n_layers = len(self.conf.layers)

        if policy.loss_scaling:
            # mixed_float16: scaled loss, fp32 unscale, overflow ->
            # skip-step-and-halve — all inside the one compiled step
            def step_fn(params_list, states_list, opt_states, ls_state,
                        it_step, ep_step, x, y, mask, fmask, rng):
                loss_fn = lambda pl: self._loss(pl, states_list, x, y,
                                                mask, rng, fmask,
                                                collect_acts=health)
                ((loss, aux), grads,
                 finite) = _precision.scaled_value_and_grad(
                    loss_fn, ls_state, params_list)
                raw_grads = grads
                grads = self._clip_grads(grads)
                new_params, new_opt = self._apply_updates(
                    params_list, opt_states, grads, it_step, ep_step)
                (new_params, new_opt, new_states,
                 new_ls) = _precision.guard_scaled_step(
                    policy, ls_state, finite,
                    [(new_params, params_list), (new_opt, opt_states),
                     (aux[0], states_list)])
                if health:
                    # post-guard params: a handled overflow reads
                    # update_ratio 0, and the handled flag tells the
                    # host not to report it as model sickness
                    h = _model_health.device_stats(
                        range(n_layers), raw_grads, new_params,
                        params_list, aux[2],
                        handled=jnp.logical_not(finite))
                    return (new_params, new_states, new_opt, new_ls,
                            aux[1], h)
                return new_params, new_states, new_opt, new_ls, aux[1]

            jitted = _telemetry.instrument_jit(
                "mln_step", jax.jit(step_fn, donate_argnums=(0, 1, 2, 3)))
            self._step_cache[key] = jitted
            return jitted

        def step_fn(params_list, states_list, opt_states, it_step, ep_step,
                    x, y, mask, fmask, rng):
            loss_fn = lambda pl: self._loss(pl, states_list, x, y, mask, rng,
                                            fmask, collect_acts=health)
            (loss, aux), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params_list)
            raw_grads = grads
            grads = self._clip_grads(grads)
            new_params, new_opt = self._apply_updates(
                params_list, opt_states, grads, it_step, ep_step)
            if health:
                h = _model_health.device_stats(
                    range(n_layers), raw_grads, new_params, params_list,
                    aux[2])
                return new_params, aux[0], new_opt, aux[1], h
            return new_params, aux[0], new_opt, aux[1]

        jitted = _telemetry.instrument_jit(
            "mln_step", jax.jit(step_fn, donate_argnums=(0, 1, 2)))
        self._step_cache[key] = jitted
        return jitted

    def _get_tbptt_step(self, has_mask: bool) -> Callable:
        """Compiled tBPTT segment step: one param update per segment,
        recurrent state carried between segments (reference:
        MultiLayerNetwork#doTruncatedBPTT). Gradients stop at segment
        boundaries because carries enter the jitted step as plain inputs
        (tbptt_back_length == tbptt_fwd_length by construction here)."""
        health = self._health is not None
        key = ("tbptt", has_mask, health)
        if key in self._step_cache:
            return self._step_cache[key]
        policy = self._policy
        n_layers = len(self.conf.layers)

        if policy.loss_scaling:
            def step_fn(params_list, states_list, opt_states, ls_state,
                        carries, it_step, ep_step, x, y, mask, rng):
                loss_fn = lambda pl: self._loss_carries(
                    pl, states_list, carries, x, y, mask, rng,
                    collect_acts=health)
                ((loss, (new_states, data_loss, new_carries, act_bad)),
                 grads, finite) = _precision.scaled_value_and_grad(
                    loss_fn, ls_state, params_list)
                raw_grads = grads
                grads = self._clip_grads(grads)
                new_params, new_opt = self._apply_updates(
                    params_list, opt_states, grads, it_step, ep_step)
                # carries deliberately NOT guarded: they are activations
                # not trainable state — the next segment re-enters from
                # whatever the forward produced, and non-finite carries
                # resolve on the minibatch reset
                (new_params, new_opt, new_states,
                 new_ls) = _precision.guard_scaled_step(
                    policy, ls_state, finite,
                    [(new_params, params_list), (new_opt, opt_states),
                     (new_states, states_list)])
                if health:
                    h = _model_health.device_stats(
                        range(n_layers), raw_grads, new_params,
                        params_list, act_bad,
                        handled=jnp.logical_not(finite))
                    return (new_params, new_states, new_opt, new_ls,
                            new_carries, data_loss, h)
                return (new_params, new_states, new_opt, new_ls,
                        new_carries, data_loss)

            jitted = _telemetry.instrument_jit(
                "mln_tbptt_step",
                jax.jit(step_fn, donate_argnums=(0, 1, 2, 3, 4)))
            self._step_cache[key] = jitted
            return jitted

        def step_fn(params_list, states_list, opt_states, carries, it_step,
                    ep_step, x, y, mask, rng):
            loss_fn = lambda pl: self._loss_carries(
                pl, states_list, carries, x, y, mask, rng,
                collect_acts=health)
            (loss, (new_states, data_loss, new_carries, act_bad)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params_list)
            raw_grads = grads
            grads = self._clip_grads(grads)
            new_params, new_opt = self._apply_updates(
                params_list, opt_states, grads, it_step, ep_step)
            if health:
                h = _model_health.device_stats(
                    range(n_layers), raw_grads, new_params, params_list,
                    act_bad)
                return (new_params, new_states, new_opt, new_carries,
                        data_loss, h)
            return new_params, new_states, new_opt, new_carries, data_loss

        jitted = _telemetry.instrument_jit(
            "mln_tbptt_step", jax.jit(step_fn, donate_argnums=(0, 1, 2, 3)))
        self._step_cache[key] = jitted
        return jitted

    def _get_forward(self, train: bool, has_fmask: bool = False) -> Callable:
        key = (train, has_fmask)
        if key in self._fwd_cache:
            return self._fwd_cache[key]
        fn = _telemetry.instrument_jit("mln_forward", jax.jit(
            lambda pl, sl, x, rng, fm: self._forward(pl, sl, x, train, rng,
                                                     fm)[0]))
        self._fwd_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # public training API (reference: fit(INDArray,INDArray) / fit(iter))
    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1,
            fault_tolerance=None, auto_resume=None):
        self._check_init()
        if fault_tolerance is not None or auto_resume is not None:
            # fault-tolerant loop (util/resilience.py): preemption-safe
            # checkpointing, auto-resume, divergence rollback. Without a
            # policy the legacy path below runs bit-identically.
            from deeplearning4j_tpu.util import resilience as _resilience

            return _resilience.run_fit(self, fault_tolerance, data,
                                       labels, epochs,
                                       auto_resume=auto_resume)
        if isinstance(data, DataSetIterator):
            import time as _time

            for _ in range(epochs):
                it = iter(data)
                while True:
                    # time spent waiting on the iterator = ETL time
                    # (reference: PerformanceListener's ETL-time metric,
                    # surfaced in the training UI's system charts)
                    t0 = _time.perf_counter()
                    try:
                        ds = next(it)
                    except StopIteration:
                        break
                    self._last_etl_ms = (_time.perf_counter() - t0) * 1e3
                    _telemetry.record_phase("etl_wait", t0)
                    self._fit_batch(ds.features, ds.labels, ds.labels_mask,
                                    ds.features_mask)
                self._epoch += 1
                for l in self._listeners:
                    if hasattr(l, "onEpochEnd"):
                        l.onEpochEnd(self)
            return self
        # non-iterator paths have no ETL wait — clear any stale value a
        # previous iterator-based fit left behind (the UI would
        # otherwise chart a frozen constant)
        self._last_etl_ms = None
        if isinstance(data, DataSet):
            for _ in range(epochs):
                self._fit_batch(data.features, data.labels,
                                data.labels_mask, data.features_mask)
            return self
        if labels is None:
            raise ValueError("fit(x, y) requires labels")
        for _ in range(epochs):
            self._fit_batch(_unwrap(data), _unwrap(labels), None)
        return self

    @staticmethod
    def _validate_fmask(fm, x):
        from deeplearning4j_tpu.nn.masking import validate_features_mask

        return validate_features_mask(
            _unwrap(fm) if fm is not None else None, x)

    def _fit_batch(self, x, y, mask, features_mask=None):
        xin = _unwrap(x)
        if isinstance(xin, jax.Array) and xin.dtype == self._input_dtype:
            # already device-resident in the right dtype (device
            # prefetcher output): no host->device copy, no cast
            _telemetry.record_on_device_batch("mln")
            x = xin
        else:
            x = jnp.asarray(xin, self._input_dtype)
        y = jnp.asarray(_unwrap(y))
        fm = self._validate_fmask(features_mask, x)
        # per-timestep labels with a features mask and no explicit label
        # mask: the features mask IS the label mask (reference: RNN
        # masking conventions)
        if mask is None and fm is not None and y.ndim == 3 \
                and fm.ndim == 2 and y.shape[1] == fm.shape[1]:
            mask = fm
        m = jnp.asarray(_unwrap(mask)) if mask is not None else None
        k = self.conf.tbptt_fwd_length
        if (k and x.ndim == 3 and x.shape[1] > k
                and any(l.is_recurrent for l in self.conf.layers)):
            if fm is not None:
                raise NotImplementedError(
                    "features masks with truncated BPTT are not supported "
                    "yet — use standard BPTT")
            return self._fit_tbptt(x, y, m, k)
        self._rng_key, sub = jax.random.split(self._rng_key)
        hm = self._health
        step_fn = self._get_train_step(m is not None, fm is not None)
        t_step = time.perf_counter()
        if self._loss_scale_state is not None:
            res = step_fn(
                self.params_list, self.states_list, self.opt_states,
                self._loss_scale_state, jnp.asarray(self._iteration),
                jnp.asarray(self._epoch), x, y, m, fm, sub)
            res, health = _model_health.split_health(res, hm is not None)
            (self.params_list, self.states_list, self.opt_states,
             self._loss_scale_state, loss) = res
        else:
            res = step_fn(
                self.params_list, self.states_list, self.opt_states,
                jnp.asarray(self._iteration), jnp.asarray(self._epoch),
                x, y, m, fm, sub)
            res, health = _model_health.split_health(res, hm is not None)
            (self.params_list, self.states_list, self.opt_states,
             loss) = res
        # dispatch-side timing: the step is async, so this span is host
        # dispatch (+ compile on a cache miss), not device wall time —
        # deliberately so; blocking here would stall the pipeline
        _telemetry.record_phase("device_step", t_step)
        # keep the loss on-device: a float() here would force a host sync
        # every step and stall the dispatch pipeline (very costly over a
        # remote/tunneled accelerator); score() converts lazily
        self._score = loss
        self._iteration += 1
        self._last_batch_size = int(x.shape[0])
        # black box + request-scoped tracing: host-side only (the
        # score stays on device), disabled cost = one attribute read
        _flight.record_step("mln", self._iteration, t_step,
                            etl_ms=self._last_etl_ms)
        _tracing.record_train_step("mln", self._iteration, t_step)
        # device-array references for listeners that recompute
        # gradients (StatsListener collect_gradients — the reference's
        # per-iteration gradient reports; free to keep, they alias the
        # arrays already on device)
        self._last_fit_batch = (x, y, m, fm, sub)
        _telemetry.sample_device_memory()
        if hm is not None:
            # records the device-side tree; syncs (one small transfer)
            # only on every `frequency`-th step
            hm.on_step(self, health, site="mln", jit_site="mln_step")
        if self._loss_scale_state is not None:
            # mirror loss-scale/overflow counters into telemetry (one
            # device->host sync per step — mixed_float16 only; disable
            # telemetry to trade observability for dispatch pipelining)
            self._ls_seen = _precision.record_loss_scale(
                "mln", self._loss_scale_state, self._ls_seen)
        self._panic_check()
        if self._listeners:
            t_l = time.perf_counter()
            for l in self._listeners:
                l.iterationDone(self, self._iteration, self._epoch)
            _telemetry.record_phase("listener_host", t_l)

    def _panic_check(self):
        """NaN/Inf panic hook (reference: OpProfiler NAN_PANIC et al. —
        per-op there, per-step here since the step is one executable)."""
        from deeplearning4j_tpu.profiler import (
            OpProfiler, ProfilerMode, check_numerics,
        )
        cfg = OpProfiler.getInstance().config
        if cfg.mode in (ProfilerMode.DISABLED, ProfilerMode.OPERATIONS):
            return
        # under dynamic loss scaling a non-finite LOSS can be a handled
        # overflow (step skipped, scale halved) — say so in the panic
        ls_ctx = _precision.loss_scale_context(self._loss_scale_state)
        check_numerics(self._score, cfg.mode,
                       f"in score at iteration {self._iteration}{ls_ctx}")
        if cfg.check_params:
            check_numerics(self.params_list, cfg.mode,
                           f"in params at iteration {self._iteration}"
                           f"{ls_ctx}")

    def _fit_tbptt(self, x, y, mask, k: int):
        """Truncated BPTT over the time axis (reference:
        MultiLayerNetwork#doTruncatedBPTT — split [N,T,*] into length-k
        segments, update params per segment, carry RNN state forward,
        reset state at the start of each minibatch)."""
        if y.ndim < 3:
            raise ValueError(
                "tBPTT requires per-timestep labels [N,T,C] "
                "(use RnnOutputLayer)")
        n, t = x.shape[0], x.shape[1]
        try:
            # carries are activations: compute dtype, not master dtype
            carries = [
                (l.init_carry(n, self._cd(i)) if l.is_recurrent else None)
                for i, l in enumerate(self.conf.layers)]
        except NotImplementedError:
            raise ValueError(
                "Truncated BPTT is not supported with Bidirectional layers "
                "(the backward direction needs the full sequence) — use "
                "standard BPTT") from None
        hm = self._health
        step_fn = self._get_tbptt_step(mask is not None)
        for t0 in range(0, t, k):
            xc = x[:, t0:t0 + k]
            yc = y[:, t0:t0 + k]
            mc = mask[:, t0:t0 + k] if mask is not None else None
            self._rng_key, sub = jax.random.split(self._rng_key)
            t_step = time.perf_counter()
            if self._loss_scale_state is not None:
                res = step_fn(
                    self.params_list, self.states_list, self.opt_states,
                    self._loss_scale_state, carries,
                    jnp.asarray(self._iteration), jnp.asarray(self._epoch),
                    xc, yc, mc, sub)
                res, health = _model_health.split_health(
                    res, hm is not None)
                (self.params_list, self.states_list, self.opt_states,
                 self._loss_scale_state, carries, loss) = res
            else:
                res = step_fn(
                    self.params_list, self.states_list, self.opt_states,
                    carries, jnp.asarray(self._iteration),
                    jnp.asarray(self._epoch), xc, yc, mc, sub)
                res, health = _model_health.split_health(
                    res, hm is not None)
                (self.params_list, self.states_list, self.opt_states,
                 carries, loss) = res
            _telemetry.record_phase("device_step", t_step)
            self._score = loss
            self._iteration += 1
            self._last_batch_size = int(xc.shape[0])
            _flight.record_step("mln_tbptt", self._iteration, t_step)
            _tracing.record_train_step("mln_tbptt", self._iteration,
                                       t_step)
            if hm is not None:
                hm.on_step(self, health, site="mln",
                           jit_site="mln_tbptt_step")
            if self._loss_scale_state is not None:
                self._ls_seen = _precision.record_loss_scale(
                    "mln", self._loss_scale_state, self._ls_seen)
            self._panic_check()
            if self._listeners:
                t_l = time.perf_counter()
                for l in self._listeners:
                    l.iterationDone(self, self._iteration, self._epoch)
                _telemetry.record_phase("listener_host", t_l)

    # ------------------------------------------------------------------
    # layerwise unsupervised pretraining (reference:
    # MultiLayerNetwork#pretrain / #pretrainLayer — SURVEY.md §2.19;
    # the VAE/AutoEncoder pretrain path)
    # ------------------------------------------------------------------
    def _prefix_activations(self, idx, params_list, states_list, a):
        """Inference-mode forward through layers [0, idx) plus layer
        idx's input preprocessor — the frozen feature extractor under
        pretrainLayer/reconstructionLogProbability. Pure: safe inside
        jit."""
        for j, lay in enumerate(self.conf.layers[:idx]):
            tag = self.conf.preprocessors.get(j)
            if tag:
                a = apply_preprocessor(tag, a)
            a = self._cast_a(a, j)
            a, _ = lay.apply(self._cast_p(params_list[j], j),
                             states_list[j], a, False, None)
        tag = self.conf.preprocessors.get(idx)
        if tag:
            a = apply_preprocessor(tag, a)
        return a

    def _get_pretrain_step(self, idx: int) -> Callable:
        if idx in self._pretrain_cache:
            return self._pretrain_cache[idx]
        layer = self.conf.layers[idx]

        def step_fn(p_i, prefix_params, states_list, opt_state, it_step,
                    x, rng):
            # frozen-prefix features, inference mode, inside the SAME
            # compiled program (no separate feature-extraction pass)
            a = self._prefix_activations(idx, prefix_params, states_list,
                                         x)

            def loss_fn(p):
                if layer.weight_noise is not None and rng is not None:
                    p = layer.weight_noise.apply(p, rng)
                loss = layer.unsupervised_loss(
                    self._cast_p(p, idx), self._cast_a(a, idx), rng)
                # same l1/l2 treatment fit() applies (reference:
                # pretraining includes regularization in the score);
                # regularization reads the MASTER params
                for k, v in p.items():
                    if k in _REGULARIZED_KEYS:
                        if layer.l1:
                            loss = loss + layer.l1 * jnp.sum(jnp.abs(v))
                        if layer.l2:
                            loss = loss + 0.5 * layer.l2 * jnp.sum(v * v)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(p_i)
            grads = self._clip_grads([grads])[0]
            updates, new_opt = apply_updater(self._updaters[idx],
                                             opt_state, grads, p_i,
                                             it_step)
            new_p = jax.tree_util.tree_map(lambda p, u: p - u, p_i,
                                           updates)
            return apply_constraints(layer, new_p), new_opt, loss

        jitted = _telemetry.instrument_jit("mln_pretrain",
                                           jax.jit(step_fn))
        self._pretrain_cache[idx] = jitted
        return jitted

    def pretrainLayer(self, idx: int, data, epochs: int = 1):
        """Unsupervised training of ONE layer (reference:
        MultiLayerNetwork#pretrainLayer(int, DataSetIterator)): lower
        layers act as a frozen feature extractor; only layer ``idx``'s
        params (and its updater state) change. ``data`` is features —
        an array, DataSet or DataSetIterator (labels ignored)."""
        self._check_init()
        layer = self.conf.layers[idx]
        if not hasattr(layer, "unsupervised_loss"):
            raise ValueError(
                f"layer {idx} ({type(layer).__name__}) is not "
                "pretrainable — only layers with an unsupervised loss "
                "(VariationalAutoencoder, AutoEncoder) support "
                "pretrainLayer")
        step = self._get_pretrain_step(idx)

        def batches():
            if isinstance(data, DataSetIterator):
                for ds in data:
                    yield ds.features
            elif isinstance(data, DataSet):
                yield data.features
            else:
                yield data

        for _ in range(epochs):
            for xb in batches():
                x = jnp.asarray(_unwrap(xb), self._input_dtype)
                self._rng_key, sub = jax.random.split(self._rng_key)
                (self.params_list[idx], self.opt_states[idx],
                 loss) = step(self.params_list[idx], self.params_list,
                              self.states_list, self.opt_states[idx],
                              jnp.asarray(self._iteration), x, sub)
                self._score = loss
                self._iteration += 1
        return self

    def pretrain(self, data, epochs: int = 1):
        """Layerwise pretrain of every pretrainable layer, bottom-up
        (reference: MultiLayerNetwork#pretrain(DataSetIterator))."""
        for idx, layer in enumerate(self.conf.layers):
            if hasattr(layer, "unsupervised_loss"):
                self.pretrainLayer(idx, data, epochs)
        return self

    def reconstructionLogProbability(self, idx: int, x,
                                     num_samples: int = 16) -> NDArray:
        """Importance-sampled log p(x) from the VAE at layer ``idx``
        (reference: VariationalAutoencoder#reconstructionLogProbability
        — the anomaly-detection score)."""
        self._check_init()
        layer = self.conf.layers[idx]
        if not hasattr(layer, "reconstruction_log_prob"):
            raise ValueError(f"layer {idx} is not a "
                             "VariationalAutoencoder")
        xj = jnp.asarray(_unwrap(x), self._input_dtype)
        a = self._prefix_activations(idx, self.params_list,
                                     self.states_list, xj)
        self._rng_key, sub = jax.random.split(self._rng_key)
        return NDArray(layer.reconstruction_log_prob(
            self.params_list[idx], a, sub, num_samples))

    # ------------------------------------------------------------------
    # inference / scoring
    # ------------------------------------------------------------------
    def output(self, x, train: bool = False, features_mask=None) -> NDArray:
        """Reference: MultiLayerNetwork#output(INDArray, train[, mask]).
        Compiled forward; train=True uses batch statistics + dropout.
        features_mask keeps inference consistent with masked training
        (zeroed padding + masked global pooling)."""
        self._check_init()
        xj = jnp.asarray(_unwrap(x), self._input_dtype)
        fm = self._validate_fmask(features_mask, xj)
        if train:
            self._rng_key, sub = jax.random.split(self._rng_key)
        else:
            sub = None
        out = self._get_forward(train, fm is not None)(
            self.params_list, self.states_list, xj, sub, fm)
        return NDArray(out)

    def feedForward(self, x) -> List[NDArray]:
        """Per-layer activations (reference returns the full list)."""
        self._check_init()
        a = jnp.asarray(_unwrap(x), self._input_dtype)
        acts = [NDArray(a)]
        for i, layer in enumerate(self.conf.layers):
            tag = self.conf.preprocessors.get(i)
            if tag:
                a = apply_preprocessor(tag, a)
            a = self._cast_a(a, i)
            a, _ = layer.apply(self._cast_p(self.params_list[i], i),
                               self.states_list[i], a, False, None)
            acts.append(NDArray(a))
        if self._mixed and acts:
            acts[-1] = NDArray(
                _precision.cast_leaf(acts[-1].jax, self._out_dtype))
        return acts

    # ------------------------------------------------------------------
    # stateful RNN stepping (reference: MultiLayerNetwork#rnnTimeStep,
    # rnnClearPreviousState, rnnGetPreviousState — SURVEY.md §5)
    # ------------------------------------------------------------------
    def _rnn_step_forward(self, params_list, states_list, carries, x):
        conf = self.conf
        a = x
        new_carries = []
        for i, layer in enumerate(conf.layers):
            tag = conf.preprocessors.get(i)
            if tag:
                a = apply_preprocessor(tag, a)
            a = self._cast_a(a, i)
            p_i = self._cast_p(params_list[i], i)
            if layer.is_recurrent:
                a, _, c = layer.apply_with_carry(
                    p_i, states_list[i], carries[i], a, False, None)
            else:
                a, _ = layer.apply(p_i, states_list[i], a, False, None)
                c = None
            new_carries.append(c)
        if self._mixed:
            a = _precision.cast_leaf(a, self._out_dtype)
        return a, new_carries

    def rnnTimeStep(self, x) -> NDArray:
        """One (or more) timesteps of stateful inference: hidden state is
        kept across calls so long sequences can be generated step by step
        without re-running history. 2-D input [N,F] means a single step
        and returns [N,out]; 3-D [N,T,F] steps T times, returns [N,T,out]."""
        self._check_init()
        xj = jnp.asarray(_unwrap(x), self._input_dtype)
        single = xj.ndim == 2
        if single:
            xj = xj[:, None, :]
        n = xj.shape[0]
        if self._rnn_carries is not None and self._rnn_batch != n:
            raise ValueError(
                f"rnnTimeStep batch size changed ({self._rnn_batch} -> {n}) "
                "with stored state — call rnnClearPreviousState() first "
                "(reference behavior: mini-batch mismatch is an error)")
        if self._rnn_carries is None:
            self._rnn_carries = [
                (l.init_carry(n, self._cd(i)) if l.is_recurrent else None)
                for i, l in enumerate(self.conf.layers)]
            self._rnn_batch = n
        if "rnn_step" not in self._fwd_cache:
            self._fwd_cache["rnn_step"] = _telemetry.instrument_jit(
                "mln_rnn_step", jax.jit(self._rnn_step_forward))
        out, self._rnn_carries = self._fwd_cache["rnn_step"](
            self.params_list, self.states_list, self._rnn_carries, xj)
        if single and out.ndim == 3:
            out = out[:, 0]
        return NDArray(out)

    rnn_time_step = rnnTimeStep

    def rnnClearPreviousState(self) -> None:
        self._rnn_carries = None
        self._rnn_batch = 0

    def rnnGetPreviousState(self, layer_idx: int):
        """Stored hidden state of one layer (LSTM: (h, c); SimpleRnn: h),
        or None if stateless / no step taken yet."""
        if self._rnn_carries is None:
            return None
        return self._rnn_carries[layer_idx]

    def rnnSetPreviousState(self, layer_idx: int, state) -> None:
        if self._rnn_carries is None:
            raise RuntimeError("No rnnTimeStep state yet — step once or "
                               "set all layers explicitly")
        self._rnn_carries[layer_idx] = state

    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Last minibatch loss, or loss on a provided DataSet."""
        if dataset is None:
            return float(self._score)
        self._check_init()
        loss, _ = self._loss(self.params_list, self.states_list,
                             jnp.asarray(dataset.features,
                                         self._input_dtype),
                             jnp.asarray(dataset.labels),
                             dataset.labels_mask, None)
        return float(loss)

    def backpropGradient(self, x, external_errors, train: bool = True,
                         features_mask=None):
        """Backprop EXTERNAL errors through the whole net (reference:
        MultiLayerNetwork#backpropGradient(epsilon, workspaceMgr) — the
        embed-in-a-custom-training-loop workflow: the caller owns the
        loss, hands dL/dOutput here, and receives (parameter gradients,
        epsilon at the input)).

        TPU-first: one ``jax.vjp`` over the same compiled train-mode
        forward ``output(train=True)`` uses, so the whole
        forward+backward is XLA-fused; gradients come back in the
        ``params_list`` pytree layout (what ``updater.apply`` and
        ``computeGradientAndScore`` use)."""
        self._check_init()
        xj = jnp.asarray(_unwrap(x), self._input_dtype)
        err = jnp.asarray(_unwrap(external_errors), self._out_dtype)
        fm = self._validate_fmask(features_mask, xj)
        saved_key = self._rng_key
        if train:
            self._rng_key, sub = jax.random.split(self._rng_key)
        else:
            sub = None
        fwd = self._get_forward(train, fm is not None)
        out, vjp = jax.vjp(
            lambda pl, xx: fwd(pl, self.states_list, xx, sub, fm),
            self.params_list, xj)
        if err.shape != out.shape:
            self._rng_key = saved_key   # failed call must not advance
            #                             the dropout stream
            raise ValueError(
                f"external_errors shape {err.shape} must match the "
                f"network output shape {out.shape}")
        grads, eps = vjp(err)
        return grads, NDArray(eps)

    def computeGradientAndScore(self, x, y):
        """(gradients, score) — the seam gradient-check tests use
        (reference: MultiLayerNetwork#computeGradientAndScore)."""
        self._check_init()
        x = jnp.asarray(_unwrap(x), self._input_dtype)
        y = jnp.asarray(_unwrap(y))
        loss_fn = lambda pl: self._loss(pl, self.states_list, x, y, None, None)[0]
        loss, grads = jax.value_and_grad(loss_fn)(self.params_list)
        return grads, float(loss)

    def evaluate(self, iterator: DataSetIterator, batch_output=None):
        """Classification evaluation (reference: MultiLayerNetwork#evaluate)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = Evaluation()
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            ev.eval(ds.labels, out.jax, mask=_eval_mask(ds))
        return ev

    def evaluateROC(self, iterator: DataSetIterator, threshold_steps=0):
        """Binary ROC/AUC (reference: MultiLayerNetwork#evaluateROC;
        expects a 1- or 2-column probability output). threshold_steps
        is accepted for API parity but the sweep is always EXACT
        (thresholdSteps=0 mode — strictly more accurate)."""
        from deeplearning4j_tpu.evaluation import ROC

        roc = ROC()
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            roc.eval(ds.labels, out.jax, mask=_eval_mask(ds))
        return roc

    def evaluateROCMultiClass(self, iterator: DataSetIterator,
                              threshold_steps=0):
        """One-vs-all ROC per class (reference:
        MultiLayerNetwork#evaluateROCMultiClass; exact sweep)."""
        from deeplearning4j_tpu.evaluation import ROCMultiClass

        roc = ROCMultiClass()
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            roc.eval(ds.labels, out.jax, mask=_eval_mask(ds))
        return roc

    def evaluateRegression(self, iterator: DataSetIterator):
        from deeplearning4j_tpu.evaluation import RegressionEvaluation

        ev = RegressionEvaluation()
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            mask = ds.labels_mask
            if mask is None and ds.features_mask is not None \
                    and np.asarray(ds.labels).ndim == 3:
                mask = ds.features_mask
            ev.eval(ds.labels, out.jax, mask=mask)
        return ev

    # ------------------------------------------------------------------
    # parameter access (reference: params()/setParams() flat views)
    # ------------------------------------------------------------------
    def _flat_order(self):
        """Deterministic (layer, key) order for the flat param vector."""
        order = []
        for i, p in enumerate(self.params_list):
            for k in sorted(p):
                order.append((i, k))
        return order

    def params(self) -> NDArray:
        """Single flat param vector (reference's flat view — here a copy;
        mutation goes through setParams, not aliasing)."""
        self._check_init()
        parts = [self.params_list[i][k].ravel() for i, k in self._flat_order()]
        return NDArray(jnp.concatenate(parts)) if parts else NDArray(jnp.zeros(0))

    def setParams(self, flat) -> None:
        self._check_init()
        v = _unwrap(flat)
        off = 0
        for i, k in self._flat_order():
            cur = self.params_list[i][k]
            n = cur.size
            self.params_list[i][k] = v[off:off + n].reshape(cur.shape).astype(cur.dtype)
            off += n
        if off != v.size:
            raise ValueError(f"Param length mismatch: {off} vs {v.size}")

    def numParams(self) -> int:
        self._check_init()
        return sum(int(l.size) for p in self.params_list
                   for l in jax.tree_util.tree_leaves(p))

    def paramTable(self) -> dict:
        """{'0_W': array, ...} flat name map (reference: paramTable())."""
        self._check_init()
        return {f"{i}_{k}": NDArray(self.params_list[i][k])
                for i, k in self._flat_order()}

    # ------------------------------------------------------------------
    # listeners / misc (reference: setListeners, summary)
    # ------------------------------------------------------------------
    def setListeners(self, *listeners):
        self._listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self._listeners.extend(listeners)
        return self

    def setHealthMonitor(self, monitor) -> "MultiLayerNetwork":
        """Attach (or with None, detach) an in-step HealthMonitor
        (profiler/model_health.py). Toggling costs exactly one extra
        compile per jit site; attached, every train step also emits
        per-layer grad/update/param stats + NaN provenance, fetched
        once every ``monitor.frequency`` steps."""
        self._health = monitor
        return self

    def getHealthMonitor(self):
        return self._health

    def getListeners(self):
        return list(self._listeners)

    def getIterationCount(self) -> int:
        return self._iteration

    def getEpochCount(self) -> int:
        return self._epoch

    def summary(self) -> str:
        self._check_init()
        lines = [f"{'idx':<4}{'layer':<28}{'params':>12}  out_type"]
        it = self.conf.input_type
        total = 0
        for i, layer in enumerate(self.conf.layers):
            n = sum(int(l.size) for l in jax.tree_util.tree_leaves(self.params_list[i]))
            total += n
            ot = layer.output_type(it) if it else None
            lines.append(f"{i:<4}{type(layer).__name__:<28}{n:>12,}  "
                         f"{(ot.kind + str(ot.example_shape())) if ot else '?'}")
            it = ot
        lines.append(f"Total params: {total:,}")
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        m = MultiLayerNetwork(self.conf)
        if self.params_list is not None:
            m.init()
            m.params_list = jax.tree_util.tree_map(lambda a: a, self.params_list)
            m.states_list = jax.tree_util.tree_map(lambda a: a, self.states_list)
            m.opt_states = jax.tree_util.tree_map(lambda a: a, self.opt_states)
            if self._loss_scale_state is not None:
                m._loss_scale_state = jax.tree_util.tree_map(
                    lambda a: a, self._loss_scale_state)
                m._ls_seen = self._ls_seen
        return m
