from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork

__all__ = ["MultiLayerNetwork"]
