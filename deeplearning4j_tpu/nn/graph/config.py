"""ComputationGraphConfiguration + GraphBuilder (reference:
org/deeplearning4j/nn/conf/ComputationGraphConfiguration.java and its
GraphBuilder — SURVEY.md §2.21).

API kept: graphBuilder().addInputs(...).addLayer(name, conf, *inputs)
.addVertex(name, vertex, *inputs).setOutputs(...).setInputTypes(...)
.build(). Build performs topo sort, type inference (with automatic
flatten preprocessors between conv and dense, like the reference's
setInputTypes), and JSON round-trip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.common import serde
from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.learning.updaters import Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, Layer, LossLayer, OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.graph.vertices import (
    GraphVertex, LayerVertex, PreprocessorVertex,
)


@serializable
@dataclasses.dataclass
class GraphNode:
    name: str = ""
    vertex: Any = None
    inputs: List = dataclasses.field(default_factory=list)


@serializable
@dataclasses.dataclass
class ComputationGraphConfiguration:
    nodes: List = dataclasses.field(default_factory=list)  # topo-sorted
    network_inputs: List = dataclasses.field(default_factory=list)
    network_outputs: List = dataclasses.field(default_factory=list)
    input_types: List = dataclasses.field(default_factory=list)
    seed: int = 12345
    updater: Any = dataclasses.field(default_factory=lambda: Sgd())
    weight_init: str = "xavier"
    l1: float = 0.0
    l2: float = 0.0
    dtype: str = "float32"
    #: mixed-precision policy (None = legacy single-dtype mode; see
    #: nn/precision.py and MultiLayerConfiguration.precision)
    precision: Optional[Any] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return serde.from_json(s)

    @staticmethod
    def graphBuilder() -> "GraphBuilder":
        return GraphBuilder()


class GraphBuilder:
    def __init__(self):
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: Dict[str, GraphNode] = {}
        self._order: List[str] = []
        self._input_types: List[InputType] = []
        self._seed = 12345
        self._updater = Sgd()
        self._weight_init = "xavier"
        self._l1 = 0.0
        self._l2 = 0.0
        self._dtype = "float32"
        self._precision = None
        self._grad_norm = None
        self._grad_norm_t = 1.0

    # -- fluent config --------------------------------------------------
    def seed(self, s):
        self._seed = int(s)
        return self

    def updater(self, u):
        self._updater = u
        return self

    def weightInit(self, w):
        self._weight_init = w.value if hasattr(w, "value") else str(w)
        return self

    def l2(self, v):
        self._l2 = float(v)
        return self

    def l1(self, v):
        self._l1 = float(v)
        return self

    def dataType(self, dt):
        self._dtype = dt.value if hasattr(dt, "value") else str(dt)
        return self

    def precision(self, policy):
        """Mixed-precision policy (preset name or PrecisionPolicy) —
        see MultiLayerConfiguration.precision."""
        self._precision = policy
        return self

    def gradientNormalization(self, mode, threshold=1.0):
        self._grad_norm = mode
        self._grad_norm_t = threshold
        return self

    # -- graph assembly -------------------------------------------------
    def addInputs(self, *names) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def addLayer(self, name: str, layer: Layer, *inputs) -> "GraphBuilder":
        return self.addVertex(name, LayerVertex(layer=layer), *inputs)

    def layer(self, name, layer, *inputs) -> "GraphBuilder":
        return self.addLayer(name, layer, *inputs)

    def addVertex(self, name: str, vertex: GraphVertex, *inputs) -> "GraphBuilder":
        if name in self._nodes or name in self._inputs:
            raise ValueError(f"Duplicate node name: {name}")
        self._nodes[name] = GraphNode(name=name, vertex=vertex,
                                      inputs=list(inputs))
        self._order.append(name)
        return self

    def setOutputs(self, *names) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def setInputTypes(self, *its) -> "GraphBuilder":
        self._input_types = list(its)
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs or not self._outputs:
            raise ValueError("Graph needs addInputs(...) and setOutputs(...)")
        # topo sort (Kahn) — validates the DAG
        indeg = {n: 0 for n in self._order}
        for n in self._order:
            for src in self._nodes[n].inputs:
                if src not in self._inputs and src not in self._nodes:
                    raise ValueError(f"Node {n} references unknown input {src}")
                if src in self._nodes:
                    indeg[n] += 1
        ready = [n for n in self._order if indeg[n] == 0]
        topo: List[str] = []
        deps = {n: [m for m in self._order
                    if n in self._nodes[m].inputs] for n in self._order}
        while ready:
            n = ready.pop(0)
            topo.append(n)
            for m in deps[n]:
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
        if len(topo) != len(self._order):
            raise ValueError("Graph has a cycle")

        # type inference + default inheritance + preprocessor insertion
        types: Dict[str, InputType] = {}
        if self._input_types:
            for name, it in zip(self._inputs, self._input_types):
                types[name] = it

        final_nodes: List[GraphNode] = []

        for name in topo:
            node = self._nodes[name]
            v = node.vertex
            if isinstance(v, LayerVertex):
                layer = v.layer
                if layer.weight_init is None:
                    layer.weight_init = self._weight_init
                if layer.l1 is None:
                    layer.l1 = self._l1
                if layer.l2 is None:
                    layer.l2 = self._l2
            if types:
                in_types = [types[s] for s in node.inputs]
                if isinstance(v, LayerVertex) and isinstance(v.layer, DenseLayer) \
                        and in_types[0].kind == "convolutional":
                    pre_name = f"{name}-flatten"
                    it0 = in_types[0]
                    pre = GraphNode(name=pre_name,
                                    vertex=PreprocessorVertex(tag="flatten"),
                                    inputs=list(node.inputs))
                    final_nodes.append(pre)
                    types[pre_name] = InputType.feedForward(
                        it0.height * it0.width * it0.channels)
                    node.inputs = [pre_name]
                    in_types = [types[pre_name]]
                if isinstance(v, LayerVertex):
                    layer = v.layer
                    it0 = in_types[0]
                    if hasattr(layer, "n_in") and getattr(layer, "n_in", 0) in (0, None):
                        layer.n_in = (it0.channels if it0.kind == "convolutional"
                                      else it0.size)
                types[name] = v.output_type(in_types)
            final_nodes.append(node)

        return ComputationGraphConfiguration(
            nodes=final_nodes,
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            seed=self._seed,
            updater=self._updater,
            weight_init=self._weight_init,
            l1=self._l1,
            l2=self._l2,
            dtype=self._dtype,
            precision=self._precision,
            gradient_normalization=self._grad_norm,
            gradient_normalization_threshold=self._grad_norm_t,
        )
