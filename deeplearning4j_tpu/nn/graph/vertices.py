"""Graph vertices (reference: org/deeplearning4j/nn/graph/vertex/impl/**
— MergeVertex, ElementWiseVertex (residual connections for ResNet50),
SubsetVertex, ScaleVertex, PreprocessorVertex. SURVEY.md §2.21).

A vertex is a (possibly parameterless) node taking >=1 input arrays.
LayerVertex wraps a layer config — the common case.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.nn.conf.inputs import InputType


@dataclasses.dataclass
class GraphVertex:
    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def init_params(self, key, input_types, dtype) -> dict:
        return {}

    def init_state(self, input_types, dtype) -> dict:
        return {}

    def apply(self, params, state, inputs: list, train: bool, rng):
        raise NotImplementedError


@serializable
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    """Wraps a layer config as a single-input vertex."""

    layer: object = None

    def output_type(self, input_types):
        return self.layer.output_type(input_types[0])

    def init_params(self, key, input_types, dtype):
        return self.layer.init_params(key, input_types[0], dtype)

    def init_state(self, input_types, dtype):
        return self.layer.init_state(input_types[0], dtype)

    def apply(self, params, state, inputs, train, rng):
        return self.layer.apply(params, state, inputs[0], train, rng)


@serializable
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis (reference: MergeVertex
    concatenates along dim 1 in NCHW — here last axis in NHWC/NTF)."""

    def output_type(self, its):
        it = its[0]
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width,
                                           sum(i.channels for i in its))
        if it.kind == "recurrent":
            return InputType.recurrent(sum(i.size for i in its),
                                       it.timeseries_length)
        return InputType.feedForward(sum(i.size for i in its))

    def apply(self, params, state, inputs, train, rng):
        return jnp.concatenate(inputs, axis=-1), state


@serializable
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise combine (reference ops: Add, Subtract, Product,
    Average, Max) — the residual-sum vertex in ResNet."""

    op: str = "Add"

    def apply(self, params, state, inputs, train, rng):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown elementwise op: {self.op}")
        return out, state


@serializable
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, params, state, inputs, train, rng):
        return inputs[0] * self.scale, state


@serializable
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """Unit-normalize each example (reference:
    graph/vertex/impl/L2NormalizeVertex — the FaceNet embedding head).
    Like the reference, rank>2 inputs normalize over ALL non-batch
    dimensions jointly, not just the channel axis."""

    eps: float = 1e-10

    def apply(self, params, state, inputs, train, rng):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.maximum(
            jnp.sum(x * x, axis=axes, keepdims=True), self.eps))
        return x / n, state


@serializable
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (reference: SubsetVertex)."""

    frm: int = 0
    to: int = 0

    def output_type(self, its):
        it = its[0]
        n = self.to - self.frm + 1
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timeseries_length)
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feedForward(n)

    def apply(self, params, state, inputs, train, rng):
        return inputs[0][..., self.frm:self.to + 1], state


@serializable
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Standalone reshape vertex carrying a preprocessor tag."""

    tag: str = "flatten"

    def output_type(self, its):
        it = its[0]
        if self.tag == "flatten":
            return InputType.feedForward(it.flat_size()
                                         if it.kind != "convolutional"
                                         else it.height * it.width * it.channels)
        if self.tag.startswith("to_conv:"):
            h, w, c = (int(v) for v in self.tag.split(":", 1)[1].split(","))
            return InputType.convolutional(h, w, c)
        return it

    def apply(self, params, state, inputs, train, rng):
        from deeplearning4j_tpu.nn.conf.builder import apply_preprocessor

        return apply_preprocessor(self.tag, inputs[0]), state
