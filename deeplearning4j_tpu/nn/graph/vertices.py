"""Graph vertices (reference: org/deeplearning4j/nn/graph/vertex/impl/**
— MergeVertex, ElementWiseVertex (residual connections for ResNet50),
SubsetVertex, ScaleVertex, PreprocessorVertex. SURVEY.md §2.21).

A vertex is a (possibly parameterless) node taking >=1 input arrays.
LayerVertex wraps a layer config — the common case.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.nn.conf.inputs import InputType


@dataclasses.dataclass
class GraphVertex:
    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def init_params(self, key, input_types, dtype) -> dict:
        return {}

    def init_state(self, input_types, dtype) -> dict:
        return {}

    def apply(self, params, state, inputs: list, train: bool, rng):
        raise NotImplementedError


@serializable
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    """Wraps a layer config as a single-input vertex."""

    layer: object = None

    def output_type(self, input_types):
        return self.layer.output_type(input_types[0])

    def init_params(self, key, input_types, dtype):
        return self.layer.init_params(key, input_types[0], dtype)

    def init_state(self, input_types, dtype):
        return self.layer.init_state(input_types[0], dtype)

    def apply(self, params, state, inputs, train, rng):
        return self.layer.apply(params, state, inputs[0], train, rng)


@serializable
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis (reference: MergeVertex
    concatenates along dim 1 in NCHW — here last axis in NHWC/NTF)."""

    def output_type(self, its):
        it = its[0]
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width,
                                           sum(i.channels for i in its))
        if it.kind == "recurrent":
            return InputType.recurrent(sum(i.size for i in its),
                                       it.timeseries_length)
        return InputType.feedForward(sum(i.size for i in its))

    def apply(self, params, state, inputs, train, rng):
        return jnp.concatenate(inputs, axis=-1), state


@serializable
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise combine (reference ops: Add, Subtract, Product,
    Average, Max) — the residual-sum vertex in ResNet."""

    op: str = "Add"

    def apply(self, params, state, inputs, train, rng):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        elif op == "min":
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
        else:
            raise ValueError(f"Unknown elementwise op: {self.op}")
        return out, state


@serializable
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, params, state, inputs, train, rng):
        return inputs[0] * self.scale, state


@serializable
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """Unit-normalize each example (reference:
    graph/vertex/impl/L2NormalizeVertex — the FaceNet embedding head).
    Like the reference, rank>2 inputs normalize over ALL non-batch
    dimensions jointly, not just the channel axis."""

    eps: float = 1e-10

    def apply(self, params, state, inputs, train, rng):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.maximum(
            jnp.sum(x * x, axis=axes, keepdims=True), self.eps))
        return x / n, state


@serializable
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (reference: SubsetVertex)."""

    frm: int = 0
    to: int = 0

    def output_type(self, its):
        it = its[0]
        n = self.to - self.frm + 1
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timeseries_length)
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feedForward(n)

    def apply(self, params, state, inputs, train, rng):
        return inputs[0][..., self.frm:self.to + 1], state


@serializable
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[N, T, F] -> [N, F] (reference:
    graph/vertex/impl/rnn/LastTimeStepVertex — the seq2seq encoder
    head). Wire a [N, T] mask as a SECOND input to select each row's
    last real step; with one input the literal final step is taken."""

    def output_type(self, its):
        it = its[0]
        return InputType.feedForward(it.size)

    def apply(self, params, state, inputs, train, rng):
        x = inputs[0]
        if len(inputs) > 1 and inputs[1] is not None:
            mask = inputs[1]  # [N, T] 1.0 = real step
            t = x.shape[1]
            # LAST NONZERO index, not sum-1: masks with interior gaps
            # would otherwise select a masked-out step
            rev_first = jnp.argmax(jnp.flip(mask.astype(jnp.int32),
                                            axis=1), axis=1)
            idx = jnp.maximum(t - 1 - rev_first, 0)
            return jnp.take_along_axis(
                x, idx[:, None, None], axis=1)[:, 0], state
        return x[:, -1], state


@serializable
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[N, F] -> [N, T, F], T taken from a reference recurrent input
    (reference: graph/vertex/impl/rnn/DuplicateToTimeSeriesVertex —
    broadcasts the encoder's thought vector along the decoder's time
    axis in seq2seq)."""

    def output_type(self, its):
        feat, ref = its[0], its[1]
        return InputType.recurrent(feat.size, ref.timeseries_length)

    def apply(self, params, state, inputs, train, rng):
        feat, ref = inputs[0], inputs[1]
        t = ref.shape[1]
        return jnp.broadcast_to(feat[:, None, :],
                                (feat.shape[0], t, feat.shape[1])), state


@serializable
@dataclasses.dataclass
class ReverseTimeSeriesVertex(GraphVertex):
    """Reverse the time axis (reference:
    graph/vertex/impl/rnn/ReverseTimeSeriesVertex)."""

    def apply(self, params, state, inputs, train, rng):
        return jnp.flip(inputs[0], axis=1), state


@serializable
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Concatenate along the BATCH axis (reference: StackVertex — used
    for weight-shared multi-tower graphs)."""

    def output_type(self, its):
        return its[0]

    def apply(self, params, state, inputs, train, rng):
        return jnp.concatenate(inputs, axis=0), state


@serializable
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Slice one of `stack_size` equal batch segments (reference:
    UnstackVertex, the inverse of StackVertex)."""

    from_index: int = 0
    stack_size: int = 1

    def output_type(self, its):
        return its[0]

    def apply(self, params, state, inputs, train, rng):
        x = inputs[0]
        if not 0 <= self.from_index < self.stack_size:
            raise ValueError(
                f"from_index {self.from_index} not in [0, "
                f"{self.stack_size})")
        if x.shape[0] % self.stack_size != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"stack_size {self.stack_size}")
        n = x.shape[0] // self.stack_size
        return x[self.from_index * n:(self.from_index + 1) * n], state


@serializable
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Standalone reshape vertex carrying a preprocessor tag."""

    tag: str = "flatten"

    def output_type(self, its):
        it = its[0]
        if self.tag == "flatten":
            return InputType.feedForward(it.flat_size()
                                         if it.kind != "convolutional"
                                         else it.height * it.width * it.channels)
        if self.tag.startswith("to_conv:"):
            h, w, c = (int(v) for v in self.tag.split(":", 1)[1].split(","))
            return InputType.convolutional(h, w, c)
        return it

    def apply(self, params, state, inputs, train, rng):
        from deeplearning4j_tpu.nn.conf.builder import apply_preprocessor

        return apply_preprocessor(self.tag, inputs[0]), state


@serializable
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    """x + constant (reference: conf/graph/ShiftVertex)."""

    shift: float = 0.0

    def apply(self, params, state, inputs, train, rng):
        return inputs[0] + self.shift, state


@serializable
@dataclasses.dataclass
class ReshapeVertex(GraphVertex):
    """Reshape to a fixed per-example shape (reference:
    conf/graph/ReshapeVertex; batch dim preserved)."""

    shape: Optional[List[int]] = None  # per-example target shape

    def output_type(self, its):
        s = list(self.shape)
        if len(s) == 1:
            return InputType.feedForward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        if len(s) == 4:
            return InputType.convolutional3D(s[0], s[1], s[2], s[3])
        raise ValueError(f"ReshapeVertex: bad shape {self.shape}")

    def apply(self, params, state, inputs, train, rng):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape)), state


@serializable
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs per example (reference:
    conf/graph/L2Vertex — the siamese/triplet distance head)."""

    eps: float = 1e-8

    def output_type(self, its):
        return InputType.feedForward(1)

    def apply(self, params, state, inputs, train, rng):
        a, b = inputs[0], inputs[1]
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps), \
            state


@serializable
@dataclasses.dataclass
class FrozenVertex(GraphVertex):
    """Wrap any vertex so its params get no gradient (reference:
    conf/graph/FrozenVertex — transfer-learning graphs)."""

    vertex: object = None

    def output_type(self, its):
        return self.vertex.output_type(its)

    def init_params(self, key, its, dtype):
        return self.vertex.init_params(key, its, dtype)

    def init_state(self, its, dtype):
        return self.vertex.init_state(its, dtype)

    def apply(self, params, state, inputs, train, rng):
        import jax as _jax

        frozen = _jax.tree_util.tree_map(_jax.lax.stop_gradient, params)
        # frozen vertices run in inference mode (dropout/BN stats off)
        return self.vertex.apply(frozen, state, inputs, False, rng)


@serializable
@dataclasses.dataclass
class PoolHelperVertex(GraphVertex):
    """Strip the first spatial row/column (reference:
    conf/graph/PoolHelperVertex — compatibility shim for Caffe-style
    ceil-mode pooling in imported GoogLeNet-class models)."""

    def output_type(self, its):
        it = its[0]
        return InputType.convolutional(it.height - 1, it.width - 1,
                                       it.channels)

    def apply(self, params, state, inputs, train, rng):
        return inputs[0][:, 1:, 1:, :], state


@serializable
@dataclasses.dataclass
class DotProductAttentionVertex(GraphVertex):
    """Scaled dot-product attention over (query, key, value[, mask])
    inputs (reference: conf/graph/AttentionVertex family; nd4j op
    dot_product_attention). Parameterless — projections live in
    upstream layers; scale = 1/sqrt(d)."""

    def output_type(self, its):
        q, v = its[0], its[2] if len(its) > 2 else its[-1]
        return InputType.recurrent(v.size, q.timeseries_length)

    def apply(self, params, state, inputs, train, rng):
        from deeplearning4j_tpu.ops import nn as nnops

        q, k, v = inputs[0], inputs[1], inputs[2]
        mask = inputs[3] if len(inputs) > 3 and inputs[3] is not None \
            else None  # [N, S] 1.0 = attend
        return nnops.dot_product_attention(
            q, k, v, mask=mask[:, None, :] if mask is not None else None), \
            state
