"""ComputationGraph — DAG network front-end (reference:
org/deeplearning4j/nn/graph/ComputationGraph.java, ~4k LoC; topo-sorted
vertex loop in §3.2). Like MultiLayerNetwork, the whole training
iteration compiles to ONE XLA executable; the topo-sorted Python loop
unrolls at trace time, so merge/residual structure costs nothing at
runtime (XLA sees one dataflow graph).

Supports multiple inputs and multiple outputs/losses (summed, as the
reference does for multi-output training).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.learning.updaters import apply_updater
from deeplearning4j_tpu.ndarray.dtypes import DataType
from deeplearning4j_tpu.ndarray.ndarray import NDArray, _unwrap
from deeplearning4j_tpu.nn import precision as _precision
from deeplearning4j_tpu.nn.conf.constraint import apply_constraints
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.vertices import LayerVertex
from deeplearning4j_tpu.nn.conf.layers import LossLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer.network import (
    _REGULARIZED_KEYS, _eval_mask, _uses_epoch_schedule,
)
from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import model_health as _model_health
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.profiler import tracing as _tracing


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params_map: Optional[Dict[str, dict]] = None
        self.states_map: Optional[Dict[str, dict]] = None
        self.opt_states: Optional[Dict[str, Any]] = None
        self._updaters: Dict[str, Any] = {}
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self._listeners: List[Any] = []
        self._rng_key = None
        self._step_cache = {}
        self._fwd = None
        self._rnn_carries = None    # stateful rnnTimeStep hidden state
        self._rnn_batch = 0
        self._node_index = None
        # mixed-precision policy (nn/precision.py) — see the
        # MultiLayerNetwork sibling for the design notes
        self._policy = _precision.PrecisionPolicy.resolve(
            getattr(conf, "precision", None), conf.dtype)
        self._mixed = not self._policy.is_identity
        self._dtype = DataType.from_any(self._policy.param_dtype).jax
        self._input_dtype = DataType.from_any(
            self._policy.compute_dtype).jax
        self._out_dtype = DataType.from_any(
            self._policy.output_dtype).jax
        self._compute_dtypes: Dict[str, Any] = {}
        self._loss_scale_state = None
        self._ls_seen = (0, 0)
        # in-step model-health monitor (profiler/model_health.py);
        # None keeps every step builder on its legacy code path
        self._health = None

    # ------------------------------------------------------------------
    def init(self) -> "ComputationGraph":
        conf = self.conf
        if not conf.input_types:
            raise ValueError("setInputTypes(...) required before init()")
        key = jax.random.key(conf.seed)
        types = {n: it for n, it in zip(conf.network_inputs, conf.input_types)}
        self.params_map, self.states_map, self.opt_states = {}, {}, {}
        for node in conf.nodes:
            in_types = [types[s] for s in node.inputs]
            key, sub = jax.random.split(key)
            p = node.vertex.init_params(sub, in_types, self._dtype)
            s = node.vertex.init_state(in_types, self._dtype)
            self.params_map[node.name] = p
            self.states_map[node.name] = s
            upd = conf.updater
            if isinstance(node.vertex, LayerVertex) and node.vertex.layer.updater is not None:
                upd = node.vertex.layer.updater
            # frozen vertices must not be touched by param-aware updaters
            # either (AdamW weight decay mutates params at zero gradient)
            from deeplearning4j_tpu.nn.graph.vertices import FrozenVertex
            if isinstance(node.vertex, FrozenVertex):
                from deeplearning4j_tpu.learning.updaters import NoOp
                upd = NoOp()
            self._updaters[node.name] = upd
            self.opt_states[node.name] = upd.init_state(p)
            types[node.name] = node.vertex.output_type(in_types)
        self._types = types
        self._rng_key = jax.random.key(conf.seed + 7919)
        # per-vertex compute dtypes (loss heads / normalization stay
        # fp32 under mixed policies; non-layer vertices follow the
        # policy compute dtype)
        self._compute_dtypes = {
            node.name: self._policy.layer_compute_dtype(
                getattr(node.vertex, "layer", None), node.name)
            for node in conf.nodes}
        self._loss_scale_state = _precision.init_loss_scale(self._policy)
        self._ls_seen = (0, 0)
        if self._mixed:
            _precision.record_cast_count("cg", sum(
                _precision.count_casts(p, self._compute_dtypes[n])
                for n, p in self.params_map.items()))
        return self

    def _check_init(self):
        if self.params_map is None:
            raise RuntimeError("Call init() first")

    def _node_by_name(self, name: str):
        if self._node_index is None:
            self._node_index = {n.name: n for n in self.conf.nodes}
        return self._node_index[name]

    # -- mixed-precision seams (identity policies: strict no-ops) ------
    def _cast_p(self, p, name):
        """Cast one vertex's MASTER params to its compute dtype (inside
        jit: one cast per step; vjp returns fp32 master grads)."""
        return _precision.cast_tree(p, self._compute_dtypes[name]) \
            if self._mixed else p

    def _cast_xs(self, xs, name):
        """Cast the activations entering a vertex (fp32 islands cast
        up; the next reduced-precision consumer casts back down)."""
        if not self._mixed:
            return xs
        dt = self._compute_dtypes[name]
        return [_precision.cast_leaf(a, dt) for a in xs]

    def _downstream_of(self, source: str) -> set:
        """Names of nodes reachable from `source` (an input or node) —
        masked pooling must only fire on the masked input's own branch."""
        down = {source}
        for node in self.conf.nodes:  # nodes are topologically ordered
            if any(s in down for s in node.inputs):
                down.add(node.name)
        return down

    def _validate_fmasks(self, feature_masks, inputs: Dict[str, Any]):
        """Normalize/validate per-input features masks. Accepts [N,T] or
        [N,T,1] on [N,T,F] inputs; anything else raises loudly. At most
        ONE masked input (masked-pooling attribution would otherwise be
        ambiguous — raise instead of guessing)."""
        conf = self.conf
        if not feature_masks:
            return {}
        if len(feature_masks) != len(conf.network_inputs):
            raise ValueError(
                f"got {len(feature_masks)} feature masks for "
                f"{len(conf.network_inputs)} graph inputs "
                f"{conf.network_inputs} (use None placeholders)")
        from deeplearning4j_tpu.nn.masking import validate_features_mask

        fmasks = {}
        for n, m in zip(conf.network_inputs, feature_masks):
            if m is None:
                continue
            fmasks[n] = validate_features_mask(
                _unwrap(m), inputs[n], ctx=f"input {n!r}")
        if len(fmasks) > 1:
            raise NotImplementedError(
                "features masks on more than one graph input are not "
                "supported (masked-pooling attribution would be "
                "ambiguous)")
        return fmasks

    # ------------------------------------------------------------------
    def _forward_all(self, params_map, states_map, inputs: dict, train, rng,
                     fmasks_map=None):
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer

        conf = self.conf
        acts: Dict[str, Any] = dict(inputs)
        fmask = None
        masked_branch: set = set()
        for name, fm in (fmasks_map or {}).items():
            acts[name] = acts[name] * fm[..., None].astype(acts[name].dtype)
            fmask = fm
            masked_branch = self._downstream_of(name)
        new_states: Dict[str, dict] = {}
        keys = (jax.random.split(rng, len(conf.nodes))
                if rng is not None else [None] * len(conf.nodes))
        for i, node in enumerate(conf.nodes):
            xs = self._cast_xs([acts[s] for s in node.inputs], node.name)
            p_n = self._cast_p(params_map[node.name], node.name)
            v = node.vertex
            if fmask is not None and node.name in masked_branch \
                    and isinstance(v, LayerVertex) \
                    and isinstance(v.layer, GlobalPoolingLayer) \
                    and xs[0].ndim == 3:
                if xs[0].shape[1] != fmask.shape[1]:
                    # An upstream layer changed the time axis (strided
                    # Conv1D/Subsampling1D): the mask no longer lines up
                    # and unmasked pooling would silently average padded
                    # zeros into the result.
                    raise ValueError(
                        f"GlobalPoolingLayer {node.name!r}: features mask "
                        f"has {fmask.shape[1]} timesteps but the pooling "
                        f"input has {xs[0].shape[1]} — an upstream layer "
                        "changed the sequence length. Downsample/supply a "
                        "mask matching the pooled sequence length "
                        "(reference: MaskedReductionUtil).")
                out, ns = v.layer.apply_masked(
                    p_n, states_map[node.name], xs[0],
                    fmask, train, keys[i])
            else:
                out, ns = v.apply(p_n, states_map[node.name], xs, train,
                                  keys[i])
            acts[node.name] = out
            new_states[node.name] = ns
        return acts, new_states

    def _loss(self, params_map, states_map, inputs, labels_map, rng,
              masks_map=None, fmasks_map=None, collect_acts=False):
        conf = self.conf
        masks_map = masks_map or {}
        fmasks_map = fmasks_map or {}
        # per-vertex non-finite forward flags, conf.nodes order
        # (model-health provenance; None when not collecting)
        act_bad = [] if collect_acts else None
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer

        acts: Dict[str, Any] = dict(inputs)
        # features masks: zero padded timesteps at each masked input
        # (reference: setLayerMaskArrays; same policy as the MLN path).
        # Masked POOLING uses the single graph-wide mask; _fit_batch
        # rejects >1 masked input so branch/mask attribution is never
        # ambiguous.
        fmask = None
        masked_branch: set = set()
        for name, fm in fmasks_map.items():
            acts[name] = acts[name] * fm[..., None].astype(
                acts[name].dtype)
            fmask = fm
            masked_branch = self._downstream_of(name)
        new_states: Dict[str, dict] = {}
        keys = (jax.random.split(rng, len(conf.nodes))
                if rng is not None else [None] * len(conf.nodes))
        total = jnp.asarray(0.0, jnp.float32)
        for i, node in enumerate(conf.nodes):
            xs = self._cast_xs([acts[s] for s in node.inputs], node.name)
            v = node.vertex
            # fp32 master params -> per-vertex compute dtype (loss
            # heads stay fp32, so the loss + reduction run at full
            # precision under mixed policies)
            p_i = self._cast_p(params_map[node.name], node.name)
            k_i = keys[i]
            # weight noise (reference: IWeightNoise, conf/weightnoise/**)
            wn = getattr(getattr(v, "layer", None), "weight_noise", None)
            if wn is not None and k_i is not None:
                k_i, k_wn = jax.random.split(k_i)
                p_i = wn.apply(p_i, k_wn)
            # masked global pooling while the time axis still lines up
            # (only on the masked input's own branch)
            if fmask is not None and node.name in masked_branch \
                    and isinstance(v, LayerVertex) \
                    and isinstance(v.layer, GlobalPoolingLayer) \
                    and xs[0].ndim == 3 \
                    and xs[0].shape[1] == fmask.shape[1]:
                out, ns = v.layer.apply_masked(
                    p_i, states_map[node.name], xs[0], fmask, True, k_i)
                acts[node.name] = out
                new_states[node.name] = ns
                if collect_acts:
                    act_bad.append(_model_health.act_flag(out))
                continue
            if node.name in conf.network_outputs and isinstance(v, LayerVertex) \
                    and hasattr(v.layer, "loss_value"):
                lv = v.layer.loss_value(
                    p_i, states_map[node.name], xs[0],
                    labels_map[node.name], masks_map.get(node.name))
                total = total + lv
                new_states[node.name] = states_map[node.name]
                acts[node.name] = xs[0]
                if collect_acts:
                    # a loss head's provenance bit is its own loss
                    # contribution: clean inputs + non-finite loss
                    # localizes the blow-up to this head
                    act_bad.append(_model_health.act_flag(lv))
            else:
                out, ns = v.apply(p_i, states_map[node.name], xs, True, k_i)
                acts[node.name] = out
                new_states[node.name] = ns
                if collect_acts:
                    act_bad.append(_model_health.act_flag(out))
        data_loss = total
        # regularization
        reg = jnp.asarray(0.0, jnp.float32)
        for node in conf.nodes:
            if not isinstance(node.vertex, LayerVertex):
                continue
            layer = node.vertex.layer
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for k, val in params_map[node.name].items():
                if k in _REGULARIZED_KEYS:
                    if l1:
                        reg = reg + l1 * jnp.sum(jnp.abs(val))
                    if l2:
                        reg = reg + 0.5 * l2 * jnp.sum(val * val)
        if collect_acts:
            return data_loss + reg, (new_states, data_loss, act_bad)
        return data_loss + reg, (new_states, data_loss)

    def _clip(self, grads):
        mode = self.conf.gradient_normalization
        if not mode:
            return grads
        t = self.conf.gradient_normalization_threshold
        if mode == "ClipElementWiseAbsoluteValue":
            return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), grads)
        if mode == "ClipL2PerLayer":
            out = {}
            for name, g in grads.items():
                leaves = jax.tree_util.tree_leaves(g)
                if not leaves:
                    out[name] = g
                    continue
                norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-12)
                scale = jnp.minimum(1.0, t / norm)
                out[name] = jax.tree_util.tree_map(lambda l: l * scale, g)
            return out
        if mode == "RenormalizeL2PerLayer":
            out = {}
            for name, g in grads.items():
                leaves = jax.tree_util.tree_leaves(g)
                if not leaves:
                    out[name] = g
                    continue
                norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-12)
                out[name] = jax.tree_util.tree_map(lambda l: l / norm, g)
            return out
        raise ValueError(f"Unknown gradient normalization: {mode}")

    def _get_train_step(self, mask_key=frozenset(), fmask_key=frozenset()):
        # static health flag: one extra compile per site when toggled
        health = self._health is not None
        cache_key = ("step", mask_key, fmask_key, health)
        if cache_key in self._step_cache:
            return self._step_cache[cache_key]

        policy = self._policy
        node_names = [n.name for n in self.conf.nodes]

        def apply_updates(params_map, opt_states, grads, it_step,
                          ep_step):
            new_params, new_opt = {}, {}
            for name in params_map:
                step = (ep_step if _uses_epoch_schedule(self._updaters[name])
                        else it_step)
                updates, no = apply_updater(self._updaters[name],
                                            opt_states[name], grads[name],
                                            params_map[name], step)
                np_i = jax.tree_util.tree_map(
                    lambda p, u: p - u, params_map[name], updates)
                # post-update constraints (reference: BaseConstraint)
                lay = getattr(self._node_by_name(name).vertex, "layer", None)
                new_params[name] = apply_constraints(lay, np_i) \
                    if lay is not None else np_i
                new_opt[name] = no
            return new_params, new_opt

        if policy.loss_scaling:
            # mixed_float16: scaled loss, fp32 unscale, skip-and-halve
            # on overflow (see MultiLayerNetwork._get_train_step)
            def step_fn(params_map, states_map, opt_states, ls_state,
                        it_step, ep_step, inputs, labels_map, masks_map,
                        fmasks_map, rng):
                loss_fn = lambda pm: self._loss(pm, states_map, inputs,
                                                labels_map, rng,
                                                masks_map, fmasks_map,
                                                collect_acts=health)
                ((loss, aux), grads,
                 finite) = _precision.scaled_value_and_grad(
                    loss_fn, ls_state, params_map)
                raw_grads = grads
                grads = self._clip(grads)
                new_params, new_opt = apply_updates(
                    params_map, opt_states, grads, it_step, ep_step)
                (new_params, new_opt, new_states,
                 new_ls) = _precision.guard_scaled_step(
                    policy, ls_state, finite,
                    [(new_params, params_map), (new_opt, opt_states),
                     (aux[0], states_map)])
                if health:
                    h = _model_health.device_stats(
                        node_names, raw_grads, new_params, params_map,
                        aux[2], handled=jnp.logical_not(finite))
                    return (new_params, new_states, new_opt, new_ls,
                            aux[1], h)
                return new_params, new_states, new_opt, new_ls, aux[1]

            jitted = _telemetry.instrument_jit(
                "cg_step", jax.jit(step_fn, donate_argnums=(0, 1, 2, 3)))
            self._step_cache[cache_key] = jitted
            return jitted

        def step_fn(params_map, states_map, opt_states, it_step, ep_step,
                    inputs, labels_map, masks_map, fmasks_map, rng):
            loss_fn = lambda pm: self._loss(pm, states_map, inputs,
                                            labels_map, rng, masks_map,
                                            fmasks_map,
                                            collect_acts=health)
            (loss, aux), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params_map)
            raw_grads = grads
            grads = self._clip(grads)
            new_params, new_opt = apply_updates(
                params_map, opt_states, grads, it_step, ep_step)
            if health:
                h = _model_health.device_stats(
                    node_names, raw_grads, new_params, params_map,
                    aux[2])
                return new_params, aux[0], new_opt, aux[1], h
            return new_params, aux[0], new_opt, aux[1]

        jitted = _telemetry.instrument_jit(
            "cg_step", jax.jit(step_fn, donate_argnums=(0, 1, 2)))
        self._step_cache[cache_key] = jitted
        return jitted

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1,
            fault_tolerance=None, auto_resume=None):
        self._check_init()
        if fault_tolerance is not None or auto_resume is not None:
            # fault-tolerant loop (util/resilience.py); the legacy path
            # below is untouched when no policy is requested
            from deeplearning4j_tpu.util import resilience as _resilience

            return _resilience.run_fit(self, fault_tolerance, data,
                                       labels, epochs,
                                       auto_resume=auto_resume)
        from deeplearning4j_tpu.datasets.multi_dataset import (
            MultiDataSet, MultiDataSetIterator,
        )

        if isinstance(data, MultiDataSetIterator):
            if epochs > 1 and not data.resetSupported():
                raise ValueError(
                    "epochs > 1 requires a resettable MultiDataSetIterator "
                    "(reference behavior)")
            for _ in range(epochs):
                for mds in _telemetry.timed_batches(data):
                    self._fit_batch(mds.features, mds.labels,
                                    mds.labels_mask_arrays or None,
                                    mds.features_mask_arrays or None)
                self._epoch += 1
            return self
        if isinstance(data, MultiDataSet):
            for _ in range(epochs):
                self._fit_batch(data.features, data.labels,
                                data.labels_mask_arrays or None,
                                data.features_mask_arrays or None)
            return self
        if isinstance(data, DataSetIterator):
            for _ in range(epochs):
                for ds in _telemetry.timed_batches(data):
                    self._fit_batch([ds.features], [ds.labels],
                                    [ds.labels_mask], [ds.features_mask])
                self._epoch += 1
            return self
        if isinstance(data, DataSet):
            for _ in range(epochs):
                self._fit_batch([data.features], [data.labels],
                                [data.labels_mask], [data.features_mask])
            return self
        if labels is None:
            raise ValueError("fit(inputs, labels) requires labels")
        if not isinstance(data, (list, tuple)):
            data = [data]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        for _ in range(epochs):
            self._fit_batch([_unwrap(d) for d in data],
                            [_unwrap(l) for l in labels])
        return self

    def _fit_batch(self, xs: Sequence, ys: Sequence, label_masks=None,
                   feature_masks=None):
        conf = self.conf
        if len(xs) != len(conf.network_inputs):
            raise ValueError(
                f"got {len(xs)} feature arrays for "
                f"{len(conf.network_inputs)} graph inputs "
                f"{conf.network_inputs}")
        if len(ys) != len(conf.network_outputs):
            raise ValueError(
                f"got {len(ys)} label arrays for "
                f"{len(conf.network_outputs)} graph outputs "
                f"{conf.network_outputs}")
        raw_xs = [_unwrap(x) for x in xs]
        if raw_xs and all(isinstance(x, jax.Array)
                          and x.dtype == self._input_dtype
                          for x in raw_xs):
            # device-prefetched batch: jnp.asarray below is a no-op
            # (same array object), no host->device copy happens
            _telemetry.record_on_device_batch("cg")
        inputs = {n: jnp.asarray(x, self._input_dtype)
                  for n, x in zip(conf.network_inputs, raw_xs)}
        labels = {n: jnp.asarray(_unwrap(y))
                  for n, y in zip(conf.network_outputs, ys)}
        masks = {}
        if label_masks:
            if len(label_masks) != len(conf.network_outputs):
                raise ValueError(
                    f"got {len(label_masks)} label masks for "
                    f"{len(conf.network_outputs)} graph outputs "
                    f"{conf.network_outputs} (use None placeholders for "
                    "unmasked outputs)")
            for n, m in zip(conf.network_outputs, label_masks):
                if m is not None:
                    masks[n] = jnp.asarray(_unwrap(m))
        fmasks = self._validate_fmasks(feature_masks, inputs)
        self._rng_key, sub = jax.random.split(self._rng_key)
        hm = self._health
        step = self._get_train_step(frozenset(masks), frozenset(fmasks))
        t_step = time.perf_counter()
        if self._loss_scale_state is not None:
            res = step(
                self.params_map, self.states_map, self.opt_states,
                self._loss_scale_state, jnp.asarray(self._iteration),
                jnp.asarray(self._epoch), inputs, labels, masks, fmasks,
                sub)
            res, health = _model_health.split_health(res, hm is not None)
            (self.params_map, self.states_map, self.opt_states,
             self._loss_scale_state, loss) = res
        else:
            res = step(
                self.params_map, self.states_map, self.opt_states,
                jnp.asarray(self._iteration), jnp.asarray(self._epoch),
                inputs, labels, masks, fmasks, sub)
            res, health = _model_health.split_health(res, hm is not None)
            (self.params_map, self.states_map, self.opt_states,
             loss) = res
        # dispatch-side host timing (the step itself runs async on
        # device; blocking here would stall the pipeline)
        _telemetry.record_phase("device_step", t_step)
        self._score = loss  # on-device; score() converts lazily (no
        # per-step host sync — critical for dispatch pipelining)
        self._iteration += 1
        self._last_batch_size = int(
            next(iter(inputs.values())).shape[0]) if inputs else 0
        # black box + request-scoped tracing (host-side only)
        _flight.record_step("cg", self._iteration, t_step)
        _tracing.record_train_step("cg", self._iteration, t_step)
        _telemetry.sample_device_memory()
        if hm is not None:
            hm.on_step(self, health, site="cg", jit_site="cg_step")
        if self._loss_scale_state is not None:
            self._ls_seen = _precision.record_loss_scale(
                "cg", self._loss_scale_state, self._ls_seen)
        if self._listeners:
            t_l = time.perf_counter()
            for l in self._listeners:
                l.iterationDone(self, self._iteration, self._epoch)
            _telemetry.record_phase("listener_host", t_l)

    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # layerwise unsupervised pretraining (reference:
    # ComputationGraph#pretrain / #pretrainLayer(String, iter))
    # ------------------------------------------------------------------
    def _get_pretrain_step(self, name: str):
        key = ("pretrain", name)  # namespaced: vertex names share the
        if key in self._step_cache:  # cache with the "rnn_step" entry
            return self._step_cache[key]
        node = self._node_by_name(name)
        layer = getattr(node.vertex, "layer", None)
        if layer is None or not hasattr(layer, "unsupervised_loss"):
            raise ValueError(
                f"vertex {name!r} is not pretrainable — only layer "
                "vertices with an unsupervised loss "
                "(VariationalAutoencoder, AutoEncoder) support "
                "pretrainLayer")
        from deeplearning4j_tpu.learning.updaters import apply_updater
        from deeplearning4j_tpu.nn.conf.constraint import apply_constraints

        def step_fn(p_i, params_map, states_map, opt_state, it_step,
                    inputs, rng):
            # frozen-prefix activations in graph topo order up to the
            # target vertex, inside the same compiled program
            acts = dict(inputs)
            for nd in self.conf.nodes:
                if nd.name == name:
                    break
                acts[nd.name], _ = nd.vertex.apply(
                    self._cast_p(params_map[nd.name], nd.name),
                    states_map[nd.name],
                    self._cast_xs([acts[s] for s in nd.inputs], nd.name),
                    False, None)
            x = acts[node.inputs[0]]

            def loss_fn(p):
                if layer.weight_noise is not None and rng is not None:
                    p = layer.weight_noise.apply(p, rng)
                loss = layer.unsupervised_loss(
                    self._cast_p(p, name),
                    self._cast_xs([x], name)[0], rng)
                # fit()-consistent l1/l2 on the pretrained layer
                for k, v in p.items():
                    if k in _REGULARIZED_KEYS:
                        if layer.l1:
                            loss = loss + layer.l1 * jnp.sum(jnp.abs(v))
                        if layer.l2:
                            loss = loss + 0.5 * layer.l2 * jnp.sum(v * v)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(p_i)
            grads = self._clip({name: grads})[name]
            updates, new_opt = apply_updater(self._updaters[name],
                                             opt_state, grads, p_i,
                                             it_step)
            new_p = jax.tree_util.tree_map(lambda p, u: p - u, p_i,
                                           updates)
            return apply_constraints(layer, new_p), new_opt, loss

        jitted = _telemetry.instrument_jit("cg_pretrain",
                                           jax.jit(step_fn))
        self._step_cache[key] = jitted
        return jitted

    def pretrainLayer(self, name: str, data, epochs: int = 1):
        """Unsupervised training of ONE layer vertex; upstream vertices
        act as a frozen feature extractor. ``data``: features — one
        array (single-input graph), a sequence matching
        ``network_inputs``, or a (Multi)DataSet(Iterator) whose labels
        are ignored."""
        self._check_init()
        step = self._get_pretrain_step(name)
        conf = self.conf

        def feature_batches():
            from deeplearning4j_tpu.datasets.multi_dataset import (
                MultiDataSet, MultiDataSetIterator,
            )
            if isinstance(data, (MultiDataSetIterator, DataSetIterator)):
                for d in data:
                    yield (d.features if isinstance(d.features, (list,
                                                                 tuple))
                           else [d.features])
            elif isinstance(data, (MultiDataSet, DataSet)):
                f = data.features
                yield f if isinstance(f, (list, tuple)) else [f]
            elif isinstance(data, (list, tuple)):
                yield data
            else:
                yield [data]

        for _ in range(epochs):
            for xs in feature_batches():
                if len(xs) != len(conf.network_inputs):
                    raise ValueError(
                        f"expected {len(conf.network_inputs)} input "
                        f"arrays, got {len(xs)}")
                inputs = {n: jnp.asarray(_unwrap(x), self._input_dtype)
                          for n, x in zip(conf.network_inputs, xs)}
                self._rng_key, sub = jax.random.split(self._rng_key)
                (self.params_map[name], self.opt_states[name],
                 loss) = step(self.params_map[name], self.params_map,
                              self.states_map, self.opt_states[name],
                              jnp.asarray(self._iteration), inputs, sub)
                self._score = loss
                self._iteration += 1
        return self

    def pretrain(self, data, epochs: int = 1):
        """Pretrain every pretrainable layer vertex in topo order
        (reference: ComputationGraph#pretrain)."""
        for node in self.conf.nodes:
            lay = getattr(node.vertex, "layer", None)
            if lay is not None and hasattr(lay, "unsupervised_loss"):
                self.pretrainLayer(node.name, data, epochs)
        return self

    # ------------------------------------------------------------------
    # stateful RNN stepping (reference: ComputationGraph#rnnTimeStep,
    # rnnClearPreviousState — same carry semantics as MultiLayerNetwork)
    # ------------------------------------------------------------------
    def _recurrent_nodes(self):
        return [n.name for n in self.conf.nodes
                if getattr(getattr(n.vertex, "layer", None),
                           "is_recurrent", False)]

    def _rnn_step_forward(self, params_map, states_map, carries, inputs):
        acts = dict(inputs)
        new_carries = {}
        for node in self.conf.nodes:
            xs = self._cast_xs([acts[s] for s in node.inputs], node.name)
            p_n = self._cast_p(params_map[node.name], node.name)
            lay = getattr(node.vertex, "layer", None)
            if lay is not None and lay.is_recurrent:
                out, _, c = lay.apply_with_carry(
                    p_n, states_map[node.name],
                    carries[node.name], xs[0], False, None)
                new_carries[node.name] = c
            else:
                out, _ = node.vertex.apply(p_n, states_map[node.name],
                                           xs, False, None)
            acts[node.name] = out
        outs = [acts[o] for o in self.conf.network_outputs]
        if self._mixed:
            outs = [_precision.cast_leaf(o, self._out_dtype)
                    for o in outs]
        return outs, new_carries

    def rnnTimeStep(self, *xs) -> List[NDArray]:
        """One (or more) timesteps of stateful inference across the
        graph; recurrent layer vertices keep their hidden carry between
        calls. 2-D inputs [N,F] mean a single step (outputs [N,out]);
        3-D [N,T,F] steps T times. Returns one NDArray per network
        output."""
        self._check_init()
        conf = self.conf
        if len(xs) != len(conf.network_inputs):
            raise ValueError(
                f"expected {len(conf.network_inputs)} inputs, got "
                f"{len(xs)}")
        arrs = [jnp.asarray(_unwrap(x), self._input_dtype) for x in xs]
        single = arrs[0].ndim == 2
        if single:
            arrs = [a[:, None, :] if a.ndim == 2 else a for a in arrs]
        n = arrs[0].shape[0]
        if self._rnn_carries is not None and self._rnn_batch != n:
            raise ValueError(
                f"rnnTimeStep batch size changed ({self._rnn_batch} -> "
                f"{n}) with stored state — call rnnClearPreviousState() "
                "first (reference behavior)")
        if self._rnn_carries is None:
            self._rnn_carries = {
                name: self._node_by_name(name).vertex.layer.init_carry(
                    n, self._compute_dtypes[name] if self._mixed
                    else self._dtype)
                for name in self._recurrent_nodes()}
            self._rnn_batch = n
        if "rnn_step" not in self._step_cache:
            self._step_cache["rnn_step"] = _telemetry.instrument_jit(
                "cg_rnn_step", jax.jit(self._rnn_step_forward))
        inputs = {k: a for k, a in zip(conf.network_inputs, arrs)}
        outs, self._rnn_carries = self._step_cache["rnn_step"](
            self.params_map, self.states_map, self._rnn_carries, inputs)
        if single:
            outs = [o[:, 0] if o.ndim == 3 else o for o in outs]
        return [NDArray(o) for o in outs]

    def rnnClearPreviousState(self) -> None:
        self._rnn_carries = None
        self._rnn_batch = 0

    def rnnGetPreviousState(self, name: str):
        if self._rnn_carries is None:
            return None
        return self._rnn_carries.get(name)

    def output(self, *xs, feature_masks=None) -> List[NDArray]:
        """Reference: ComputationGraph#output — returns list of outputs.
        feature_masks keeps inference consistent with masked training."""
        self._check_init()
        conf = self.conf
        inputs = {n: jnp.asarray(_unwrap(x), self._input_dtype)
                  for n, x in zip(conf.network_inputs, xs)}
        fmasks = self._validate_fmasks(feature_masks, inputs)
        key = frozenset(fmasks)
        if self._fwd is None:
            self._fwd = {}
        if key not in self._fwd:
            out_dt = self._out_dtype
            self._fwd[key] = _telemetry.instrument_jit("cg_forward", jax.jit(
                lambda pm, sm, inp, fms: tuple(
                    _precision.cast_leaf(
                        self._forward_all(pm, sm, inp, False, None,
                                          fms)[0][o], out_dt)
                    for o in conf.network_outputs)))
        outs = self._fwd[key](self.params_map, self.states_map, inputs,
                              fmasks)
        return [NDArray(o) for o in outs]

    def outputSingle(self, *xs, feature_masks=None) -> NDArray:
        return self.output(*xs, feature_masks=feature_masks)[0]

    def backpropGradient(self, xs, external_errors, train: bool = True):
        """Backprop EXTERNAL errors through the graph (reference:
        ComputationGraph#backpropGradient(INDArray... epsilons) — one
        epsilon per network output, caller-owned loss). ``xs`` is a
        list of input arrays (one per network input) and
        ``external_errors`` a list of dL/dOutput arrays (one per
        network output, graph output order). Returns (gradients in the
        ``params_map`` pytree layout, {input name: epsilon}). One
        ``jax.vjp`` over the same jit-compiled forward the training
        step uses (train=True: dropout + batch statistics, like the
        reference and the MultiLayerNetwork sibling)."""
        self._check_init()
        conf = self.conf
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        if not isinstance(external_errors, (list, tuple)):
            external_errors = [external_errors]
        if len(xs) != len(conf.network_inputs):
            raise ValueError(
                f"need one input per network input "
                f"({len(conf.network_inputs)}), got {len(xs)}")
        if len(external_errors) != len(conf.network_outputs):
            raise ValueError(
                f"need one external error per network output "
                f"({len(conf.network_outputs)}), got "
                f"{len(external_errors)}")
        inputs = {n: jnp.asarray(_unwrap(x), self._input_dtype)
                  for n, x in zip(conf.network_inputs, xs)}
        errs = tuple(jnp.asarray(_unwrap(e), self._out_dtype)
                     for e in external_errors)
        saved_key = self._rng_key
        if train:
            self._rng_key, sub = jax.random.split(self._rng_key)
        else:
            sub = None
        if not hasattr(self, "_ext_fwd"):
            self._ext_fwd = {}
        if train not in self._ext_fwd:
            # signature probe: this fn is only ever called under
            # jax.vjp, where the executable cache never grows
            out_dt = self._out_dtype
            self._ext_fwd[train] = _telemetry.instrument_jit(
                "cg_ext_forward", jax.jit(
                    lambda pm, sm, inp, rng: tuple(
                        _precision.cast_leaf(
                            self._forward_all(pm, sm, inp, train, rng,
                                              {})[0][o], out_dt)
                        for o in conf.network_outputs)),
                probe="signature")
        fwd = self._ext_fwd[train]
        outs, vjp = jax.vjp(
            lambda pm, inp: fwd(pm, self.states_map, inp, sub),
            self.params_map, inputs)
        for e, o, name in zip(errs, outs, conf.network_outputs):
            if e.shape != o.shape:
                self._rng_key = saved_key   # failed call: keep
                #                             seed-for-seed streams
                raise ValueError(
                    f"external error for output {name!r} has shape "
                    f"{e.shape}, expected {o.shape}")
        grads, eps = vjp(errs)
        return grads, {n: NDArray(v) for n, v in eps.items()}

    def score(self, dataset: Optional[DataSet] = None) -> float:
        if dataset is None:
            return float(self._score)
        self._check_init()
        inputs = {self.conf.network_inputs[0]: jnp.asarray(
            dataset.features, self._input_dtype)}
        labels = {self.conf.network_outputs[0]: jnp.asarray(dataset.labels)}
        loss, _ = self._loss(self.params_map, self.states_map, inputs, labels, None)
        return float(loss)

    def evaluate(self, iterator: DataSetIterator):
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = Evaluation()
        for ds in iterator:
            fms = [ds.features_mask] if ds.features_mask is not None \
                else None
            out = self.outputSingle(ds.features, feature_masks=fms)
            mask = ds.labels_mask
            if mask is None and ds.features_mask is not None \
                    and np.asarray(ds.labels).ndim == 3:
                mask = ds.features_mask
            ev.eval(ds.labels, out.jax, mask=mask)
        return ev

    def evaluateROC(self, iterator: DataSetIterator, threshold_steps=0):
        """Binary ROC/AUC over the single graph output (reference:
        ComputationGraph#evaluateROC; exact sweep)."""
        from deeplearning4j_tpu.evaluation import ROC

        roc = ROC()
        for ds in iterator:
            fms = [ds.features_mask] if ds.features_mask is not None \
                else None
            out = self.outputSingle(ds.features, feature_masks=fms)
            roc.eval(ds.labels, out.jax, mask=_eval_mask(ds))
        return roc

    def evaluateROCMultiClass(self, iterator: DataSetIterator,
                              threshold_steps=0):
        """One-vs-all ROC per class (reference:
        ComputationGraph#evaluateROCMultiClass; exact sweep)."""
        from deeplearning4j_tpu.evaluation import ROCMultiClass

        roc = ROCMultiClass()
        for ds in iterator:
            fms = [ds.features_mask] if ds.features_mask is not None \
                else None
            out = self.outputSingle(ds.features, feature_masks=fms)
            roc.eval(ds.labels, out.jax, mask=_eval_mask(ds))
        return roc

    def evaluateRegression(self, iterator: DataSetIterator):
        from deeplearning4j_tpu.evaluation import RegressionEvaluation

        ev = RegressionEvaluation()
        for ds in iterator:
            fms = [ds.features_mask] if ds.features_mask is not None \
                else None
            out = self.outputSingle(ds.features, feature_masks=fms)
            mask = ds.labels_mask
            if mask is None and ds.features_mask is not None \
                    and np.asarray(ds.labels).ndim == 3:
                mask = ds.features_mask
            ev.eval(ds.labels, out.jax, mask=mask)
        return ev

    # ------------------------------------------------------------------
    def numParams(self) -> int:
        self._check_init()
        return sum(int(l.size) for p in self.params_map.values()
                   for l in jax.tree_util.tree_leaves(p))

    def params(self) -> NDArray:
        self._check_init()
        parts = []
        for node in self.conf.nodes:
            p = self.params_map[node.name]
            for k in sorted(p):
                parts.append(p[k].ravel())
        return NDArray(jnp.concatenate(parts)) if parts else NDArray(jnp.zeros(0))

    def setParams(self, flat):
        self._check_init()
        v = _unwrap(flat)
        off = 0
        for node in self.conf.nodes:
            p = self.params_map[node.name]
            for k in sorted(p):
                n = p[k].size
                p[k] = v[off:off + n].reshape(p[k].shape).astype(p[k].dtype)
                off += n

    def setListeners(self, *ls):
        self._listeners = list(ls)
        return self

    def addListeners(self, *ls):
        self._listeners.extend(ls)
        return self

    def setHealthMonitor(self, monitor) -> "ComputationGraph":
        """Attach (or with None, detach) an in-step HealthMonitor
        (profiler/model_health.py) — see the MultiLayerNetwork sibling."""
        self._health = monitor
        return self

    def getHealthMonitor(self):
        return self._health

    def clone(self) -> "ComputationGraph":
        """Structural copy sharing array references (reference:
        ComputationGraph#clone). Callers that keep training the source
        must copy buffers (the compiled step donates them)."""
        m = ComputationGraph(self.conf)
        if self.params_map is not None:
            m.init()
            m.params_map = jax.tree_util.tree_map(
                lambda a: a, self.params_map)
            m.states_map = jax.tree_util.tree_map(
                lambda a: a, self.states_map)
            m.opt_states = jax.tree_util.tree_map(
                lambda a: a, self.opt_states)
            if self._loss_scale_state is not None:
                m._loss_scale_state = jax.tree_util.tree_map(
                    lambda a: a, self._loss_scale_state)
                m._ls_seen = self._ls_seen
        return m

    def getIterationCount(self):
        return self._iteration

    def getEpochCount(self):
        return self._epoch

    def summary(self) -> str:
        self._check_init()
        lines = [f"{'name':<24}{'vertex':<26}{'params':>12}  inputs"]
        total = 0
        for node in self.conf.nodes:
            n = sum(int(l.size) for l in
                    jax.tree_util.tree_leaves(self.params_map[node.name]))
            total += n
            vname = (type(node.vertex.layer).__name__
                     if isinstance(node.vertex, LayerVertex)
                     else type(node.vertex).__name__)
            lines.append(f"{node.name:<24}{vname:<26}{n:>12,}  {node.inputs}")
        lines.append(f"Total params: {total:,}")
        return "\n".join(lines)
