from deeplearning4j_tpu.nn.graph.vertices import (
    DotProductAttentionVertex, DuplicateToTimeSeriesVertex,
    ElementWiseVertex, FrozenVertex, GraphVertex, L2NormalizeVertex,
    L2Vertex, LastTimeStepVertex, LayerVertex, MergeVertex,
    PoolHelperVertex, PreprocessorVertex, ReshapeVertex,
    ReverseTimeSeriesVertex, ScaleVertex, ShiftVertex, StackVertex,
    SubsetVertex, UnstackVertex,
)
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

__all__ = [
    "ComputationGraph", "ComputationGraphConfiguration", "GraphVertex",
    "LayerVertex", "MergeVertex", "ElementWiseVertex", "ScaleVertex",
    "SubsetVertex", "PreprocessorVertex", "L2NormalizeVertex",
    "LastTimeStepVertex", "DuplicateToTimeSeriesVertex",
    "ReverseTimeSeriesVertex", "StackVertex", "UnstackVertex",
    "ShiftVertex", "ReshapeVertex", "L2Vertex", "FrozenVertex",
    "PoolHelperVertex", "DotProductAttentionVertex",
]
