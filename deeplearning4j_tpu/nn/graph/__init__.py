from deeplearning4j_tpu.nn.graph.vertices import (
    ElementWiseVertex, GraphVertex, L2NormalizeVertex, LayerVertex,
    MergeVertex, ScaleVertex, SubsetVertex, PreprocessorVertex,
)
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

__all__ = [
    "ComputationGraph", "ComputationGraphConfiguration", "GraphVertex",
    "LayerVertex", "MergeVertex", "ElementWiseVertex", "ScaleVertex",
    "SubsetVertex", "PreprocessorVertex", "L2NormalizeVertex",
]
