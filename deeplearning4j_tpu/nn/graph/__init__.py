from deeplearning4j_tpu.nn.graph.vertices import (
    DuplicateToTimeSeriesVertex, ElementWiseVertex, GraphVertex,
    L2NormalizeVertex, LastTimeStepVertex, LayerVertex, MergeVertex,
    ReverseTimeSeriesVertex, ScaleVertex, StackVertex, SubsetVertex,
    UnstackVertex, PreprocessorVertex,
)
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

__all__ = [
    "ComputationGraph", "ComputationGraphConfiguration", "GraphVertex",
    "LayerVertex", "MergeVertex", "ElementWiseVertex", "ScaleVertex",
    "SubsetVertex", "PreprocessorVertex", "L2NormalizeVertex",
    "LastTimeStepVertex", "DuplicateToTimeSeriesVertex",
    "ReverseTimeSeriesVertex", "StackVertex", "UnstackVertex",
]
