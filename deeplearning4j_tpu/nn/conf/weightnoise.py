"""Weight noise (reference: org/deeplearning4j/nn/conf/weightnoise/** —
IWeightNoise: DropConnect, WeightNoise; SURVEY.md §2.18).

Applied to a layer's WEIGHT params (not biases) each training forward,
inside the compiled step. Configure via ``Layer.weight_noise``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable

#: param keys treated as weights (matches the network's regularization
#: key set; biases/norm scales are exempt, like the reference's
#: paramType==WEIGHT filter)
WEIGHT_KEYS = {"W", "RW", "dW", "pW", "Wq", "Wk", "Wv", "Wo", "Wa"}


class IWeightNoise:
    """Marker base (reference: IWeightNoise interface)."""

    def _noise_one(self, w, rng):  # pragma: no cover - interface
        raise NotImplementedError

    def apply(self, params: dict, rng):
        """Return params with noised weight entries."""
        out = dict(params)
        keys = [k for k in params if k in WEIGHT_KEYS]
        subkeys = jax.random.split(rng, max(len(keys), 1))
        for k, sk in zip(keys, subkeys):
            out[k] = self._noise_one(params[k], sk)
        return out


@serializable
@dataclasses.dataclass
class DropConnect(IWeightNoise):
    """Drop individual WEIGHTS with prob ``rate`` (reference:
    weightnoise/DropConnect; Wan et al. 2013). Inverted scaling keeps
    the expected pre-activation unchanged."""

    rate: float = 0.5

    def _noise_one(self, w, rng):
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, w.shape)
        return jnp.where(mask, w / keep, 0.0).astype(w.dtype)


@serializable
@dataclasses.dataclass
class WeightNoise(IWeightNoise):
    """Additive or multiplicative gaussian weight noise (reference:
    weightnoise/WeightNoise with a distribution + additive flag)."""

    mean: float = 0.0
    stddev: float = 0.1
    additive: bool = True

    def _noise_one(self, w, rng):
        noise = self.mean + self.stddev * jax.random.normal(rng, w.shape,
                                                            w.dtype)
        return w + noise if self.additive else w * noise
