"""Variational autoencoder + denoising autoencoder layers with
unsupervised (layerwise) pretraining.

Reference: org/deeplearning4j/nn/conf/layers/variational/
VariationalAutoencoder.java + impl org/deeplearning4j/nn/layers/
variational/VariationalAutoencoder.java (encoder/decoder MLP stacks,
reconstruction distributions Gaussian/Bernoulli, importance-sampled
``reconstructionProbability`` for anomaly detection, param groups
e0W../pZXMeanW../d0W../pXZW..) and org/deeplearning4j/nn/conf/layers/
AutoEncoder.java (denoising autoencoder: masking corruption, tied
W/W^T decoder, visible bias vb) — the two layers behind the
reference's ``MultiLayerNetwork#pretrain`` layerwise unsupervised
training (SURVEY.md §2.19/§2.20).

TPU-native design: each layer exposes ``unsupervised_loss(params, x,
rng)`` — a pure function the network jit-compiles into ONE XLA step
per pretrained layer (features from the frozen prefix are computed in
the same compiled program; the reference runs a separate Java
optimizer loop per layer). The VAE ELBO draws its reparameterization
noise from the step PRNG (counter-based, like every other stochastic
op here); ``reconstruction_log_prob`` vectorizes the K importance
samples with one batched decoder pass instead of the reference's
sequential sample loop.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable, _tuplify
from deeplearning4j_tpu.loss import LossFunction, compute_loss
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, _act
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights

# math, NOT jnp: a module-level jnp computation would initialise the
# XLA backend at import time, breaking jax.distributed.initialize()
# in multi-process workers (they import the package first)
_LOG2PI = math.log(2.0 * math.pi)


def _mlp_init(key, sizes, weight_init, dtype, prefix):
    """Param dict for a dense stack: {prefix}{i}W / {prefix}{i}b
    (reference naming: VariationalAutoencoderParamInitializer e0W..)."""
    p = {}
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        p[f"{prefix}{i}W"] = init_weights(weight_init, k, (a, b), a, b,
                                          dtype)
        p[f"{prefix}{i}b"] = jnp.zeros((b,), dtype)
    return p


def _mlp_apply(params, x, n, act, prefix):
    for i in range(n):
        x = act.fn(x @ params[f"{prefix}{i}W"] + params[f"{prefix}{i}b"])
    return x


@serializable
@dataclasses.dataclass
class VariationalAutoencoder(Layer):
    """VAE layer (reference: conf/layers/variational/
    VariationalAutoencoder). In a supervised network it acts as a
    feedforward encoder emitting the latent mean through
    ``pzx_activation``; unsupervised pretraining maximizes the ELBO.

    reconstruction_distribution: "gaussian" (pXZ head emits mean and
    log-variance per feature, 2*n_in outputs) or "bernoulli" (n_in
    logits, data expected in [0,1]).
    """

    n_in: int = 0
    n_out: int = 0  # latent size (reference: nOut == latent space size)
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction_distribution: str = "gaussian"
    #: activation applied to the Gaussian reconstruction mean
    #: (reference: GaussianReconstructionDistribution(activation))
    reconstruction_activation: str = "identity"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def __post_init__(self):
        self.encoder_layer_sizes = _tuplify(self.encoder_layer_sizes)
        self.decoder_layer_sizes = _tuplify(self.decoder_layer_sizes)

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(self.n_out)

    def _dist_size(self) -> int:
        if self.reconstruction_distribution == "gaussian":
            return 2 * self.n_in
        if self.reconstruction_distribution == "bernoulli":
            return self.n_in
        raise ValueError("reconstruction_distribution must be "
                         "'gaussian' or 'bernoulli', got "
                         f"{self.reconstruction_distribution!r}")

    def init_params(self, key, it: InputType, dtype) -> dict:
        wi = self.weight_init or WeightInit.XAVIER
        ks = jax.random.split(key, 6)
        enc = (self.n_in,) + self.encoder_layer_sizes
        dec = (self.n_out,) + self.decoder_layer_sizes
        p = _mlp_init(ks[0], enc, wi, dtype, "e")
        p.update(_mlp_init(ks[1], dec, wi, dtype, "d"))
        eL, dL = enc[-1], dec[-1]
        p["pZXMeanW"] = init_weights(wi, ks[2], (eL, self.n_out), eL,
                                     self.n_out, dtype)
        p["pZXMeanb"] = jnp.zeros((self.n_out,), dtype)
        p["pZXLogStd2W"] = init_weights(wi, ks[3], (eL, self.n_out), eL,
                                        self.n_out, dtype)
        p["pZXLogStd2b"] = jnp.zeros((self.n_out,), dtype)
        ds = self._dist_size()
        p["pXZW"] = init_weights(wi, ks[4], (dL, ds), dL, ds, dtype)
        p["pXZb"] = jnp.zeros((ds,), dtype)
        return p

    # -- pieces ---------------------------------------------------------
    def _encode(self, params, x):
        act = _act(self.activation or "identity")
        h = _mlp_apply(params, x, len(self.encoder_layer_sizes), act, "e")
        mean = h @ params["pZXMeanW"] + params["pZXMeanb"]
        log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2b"]
        return mean, log_var

    def _decode_logp(self, params, z, x):
        """log p(x|z) per example; z may be [K,N,L] (batched samples)."""
        act = _act(self.activation or "identity")
        d = _mlp_apply(params, z, len(self.decoder_layer_sizes), act, "d")
        out = d @ params["pXZW"] + params["pXZb"]
        if self.reconstruction_distribution == "bernoulli":
            # stable -BCE from logits
            return jnp.sum(x * out - jnp.logaddexp(0.0, out), axis=-1)
        mu, lv = jnp.split(out, 2, axis=-1)
        mu = _act(self.reconstruction_activation).fn(mu)
        return -0.5 * jnp.sum(
            _LOG2PI + lv + (x - mu) ** 2 * jnp.exp(-lv), axis=-1)

    # -- supervised path: latent mean as the layer activation -----------
    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        mean, _ = self._encode(params, x)
        return _act(self.pzx_activation).fn(mean), state

    # -- unsupervised path ----------------------------------------------
    def unsupervised_loss(self, params, x, rng):
        """-ELBO, averaged over the batch (the pretrain objective)."""
        mean, log_var = self._encode(params, x)
        k = self.num_samples
        eps = jax.random.normal(rng, (k,) + mean.shape, mean.dtype)
        z = mean[None] + jnp.exp(0.5 * log_var)[None] * eps
        logp = jnp.mean(self._decode_logp(params, z, x[None]), axis=0)
        kl = -0.5 * jnp.sum(1.0 + log_var - mean ** 2 - jnp.exp(log_var),
                            axis=-1)
        return jnp.mean(kl - logp)

    def reconstruction_log_prob(self, params, x, rng, num_samples=16):
        """Importance-sampled log p(x) per example (reference:
        VariationalAutoencoder#reconstructionLogProbability — the
        anomaly-detection score; higher = more 'normal')."""
        mean, log_var = self._encode(params, x)
        std = jnp.exp(0.5 * log_var)
        eps = jax.random.normal(rng, (num_samples,) + mean.shape,
                                mean.dtype)
        z = mean[None] + std[None] * eps
        log_px_z = self._decode_logp(params, z, x[None])
        log_pz = -0.5 * jnp.sum(_LOG2PI + z ** 2, axis=-1)
        log_qz = -0.5 * jnp.sum(
            _LOG2PI + log_var[None] + eps ** 2, axis=-1)
        return (jax.scipy.special.logsumexp(
            log_px_z + log_pz - log_qz, axis=0)
            - jnp.log(float(num_samples)))


@serializable
@dataclasses.dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder layer (reference: conf/layers/
    AutoEncoder). Supervised forward = encoder (dense, activation);
    unsupervised loss = reconstruct the UNCORRUPTED input from a
    masking-corrupted encoding through the tied-weight decoder
    (z = act(h @ W^T + vb)), plus an optional sparsity penalty on the
    mean hidden activation."""

    n_in: int = 0
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(self.n_out)

    def init_params(self, key, it: InputType, dtype) -> dict:
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (self.n_in, self.n_out), self.n_in, self.n_out,
                         dtype)
        return {"W": w, "b": jnp.zeros((self.n_out,), dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        act = _act(self.activation or "sigmoid")
        return act.fn(x @ params["W"] + params["b"]), state

    def unsupervised_loss(self, params, x, rng):
        act = _act(self.activation or "sigmoid")
        x_in = x
        if self.corruption_level > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level,
                                        x.shape)
            x_in = jnp.where(keep, x, 0.0)
        h = act.fn(x_in @ params["W"] + params["b"])
        recon_pre = h @ params["W"].T + params["vb"]
        loss = compute_loss(LossFunction.resolve(self.loss), x, recon_pre,
                            self.activation or "sigmoid", None)
        if self.sparsity > 0.0:
            loss = loss + self.sparsity * jnp.mean(jnp.abs(h))
        return loss
