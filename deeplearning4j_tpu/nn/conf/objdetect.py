"""YOLOv2 object-detection output layer.

Reference: org/deeplearning4j/nn/conf/layers/objdetect/Yolo2OutputLayer
+ impl org/deeplearning4j/nn/layers/objdetect/Yolo2OutputLayer (used by
TinyYOLO/YOLO2 in the zoo, SURVEY.md §2.33).

Layout differences by design (TPU NHWC):
- network activations: [N, H, W, B*(5+C)]  (reference: [mb, B*(5+C), H, W])
- labels:              [N, H, W, 4+C]      (reference: [mb, 4+C, H, W]),
  where the 4 are (x1, y1, x2, y2) in GRID units (0..W / 0..H) and the C
  are the one-hot class of the cell's object (all-zero = no object).

The whole loss is one fused XLA computation: sigmoid offsets, anchor-
scaled sizes, per-anchor IoU responsibility (argmax -> stop_gradient
one-hot, the standard differentiable-through-selection trick), the four
YOLOv2 terms with lambda_coord / lambda_no_obj weighting.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import LossLayer


@serializable
@dataclasses.dataclass
class Yolo2OutputLayer(LossLayer):
    """Parameterless YOLOv2 loss head (a LossLayer so both network
    front-ends accept it as terminal). `anchors` are (w, h) pairs in
    grid units; C is inferred from the label depth at loss time."""

    anchors: Tuple = ()
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def __post_init__(self):
        self.anchors = tuple(tuple(a) for a in self.anchors)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return it

    def apply(self, params, state, x, train, rng):
        return x, state

    # -- decoding ------------------------------------------------------
    def _decode(self, x, n_classes: int):
        """[N,H,W,B*(5+C)] -> (xy [N,H,W,B,2] absolute grid coords,
        wh [N,H,W,B,2] grid units, conf [N,H,W,B], class logits
        [N,H,W,B,C])."""
        n, h, w, _ = x.shape
        b = len(self.anchors)
        x = x.reshape(n, h, w, b, 5 + n_classes)
        # cell top-left offsets
        cy = jnp.arange(h, dtype=x.dtype).reshape(1, h, 1, 1)
        cx = jnp.arange(w, dtype=x.dtype).reshape(1, 1, w, 1)
        px = jax.nn.sigmoid(x[..., 0]) + cx
        py = jax.nn.sigmoid(x[..., 1]) + cy
        anchors = jnp.asarray(self.anchors, x.dtype)      # [B,2]
        pw = jnp.exp(x[..., 2]) * anchors[:, 0]
        ph = jnp.exp(x[..., 3]) * anchors[:, 1]
        conf = jax.nn.sigmoid(x[..., 4])
        cls_logits = x[..., 5:]
        return (jnp.stack([px, py], -1), jnp.stack([pw, ph], -1), conf,
                cls_logits)

    @staticmethod
    def _iou(xy1, wh1, xy2, wh2):
        """IoU of center-format boxes; broadcasts."""
        mins1, maxs1 = xy1 - wh1 / 2, xy1 + wh1 / 2
        mins2, maxs2 = xy2 - wh2 / 2, xy2 + wh2 / 2
        inter_min = jnp.maximum(mins1, mins2)
        inter_max = jnp.minimum(maxs1, maxs2)
        inter = jnp.prod(jnp.clip(inter_max - inter_min, 0.0, None), -1)
        a1 = jnp.prod(wh1, -1)
        a2 = jnp.prod(wh2, -1)
        return inter / jnp.maximum(a1 + a2 - inter, 1e-9)

    # -- the YOLOv2 loss ----------------------------------------------
    def loss_value(self, params, state, x, labels, mask=None):
        n, h, w, d = labels.shape
        n_classes = d - 4
        b = len(self.anchors)
        if x.shape[-1] != b * (5 + n_classes):
            raise ValueError(
                f"Yolo2OutputLayer: activations depth {x.shape[-1]} != "
                f"B*(5+C) = {b}*(5+{n_classes})")
        pxy, pwh, pconf, pcls = self._decode(x, n_classes)

        cls_1hot = labels[..., 4:]                         # [N,H,W,C]
        obj = (jnp.sum(cls_1hot, -1) > 0).astype(x.dtype)  # [N,H,W]
        x1, y1, x2, y2 = (labels[..., i] for i in range(4))
        gxy = jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2], -1)  # [N,H,W,2]
        gwh = jnp.stack([jnp.maximum(x2 - x1, 1e-6),
                         jnp.maximum(y2 - y1, 1e-6)], -1)

        # anchor responsibility: IoU of anchor shapes vs gt shape
        # (location-independent, as in the paper)
        anchors = jnp.asarray(self.anchors, x.dtype)       # [B,2]
        zeros = jnp.zeros_like(gwh)[..., None, :]          # [N,H,W,1,2]
        a_iou = self._iou(zeros, jnp.broadcast_to(
            anchors, gwh.shape[:-1] + (b, 2)), zeros, gwh[..., None, :])
        resp = jax.nn.one_hot(jnp.argmax(a_iou, -1), b, dtype=x.dtype)
        resp = jax.lax.stop_gradient(resp) * obj[..., None]  # [N,H,W,B]

        # coord loss (sqrt on sizes, as in the paper)
        dxy = jnp.sum((pxy - gxy[..., None, :]) ** 2, -1)
        dwh = jnp.sum((jnp.sqrt(pwh) - jnp.sqrt(gwh[..., None, :])) ** 2, -1)
        coord = self.lambda_coord * jnp.sum(resp * (dxy + dwh))

        # confidence: responsible boxes match their live IoU; the rest 0
        live_iou = jax.lax.stop_gradient(
            self._iou(pxy, pwh, gxy[..., None, :],
                      jnp.broadcast_to(gwh[..., None, :], pwh.shape)))
        conf_obj = jnp.sum(resp * (pconf - live_iou) ** 2)
        conf_noobj = self.lambda_no_obj * jnp.sum(
            (1.0 - resp) * pconf ** 2)

        # class loss: softmax CE on the responsible box
        logp = jax.nn.log_softmax(pcls, -1)
        ce = -jnp.sum(cls_1hot[..., None, :] * logp, -1)   # [N,H,W,B]
        cls_loss = jnp.sum(resp * ce)

        total = coord + conf_obj + conf_noobj + cls_loss
        if mask is not None:
            raise NotImplementedError("Yolo2OutputLayer does not use masks")
        return total / n


__all__ = ["Yolo2OutputLayer", "DetectedObject", "YoloUtils"]


from functools import lru_cache


@lru_cache(maxsize=32)
def _batched_nms(max_objects: int, iou_threshold: float,
                 score_threshold: float):
    """Cached jitted vmap of NMS — rebuilding jit(vmap(partial(...)))
    per call would recompile every invocation."""
    from functools import partial

    from deeplearning4j_tpu.ops.image import non_max_suppression

    return jax.jit(jax.vmap(partial(
        non_max_suppression, max_output_size=max_objects,
        iou_threshold=iou_threshold, score_threshold=score_threshold)))


class DetectedObject:
    """One decoded detection (reference:
    org/deeplearning4j/nn/layers/objdetect/DetectedObject). Coordinates
    are in GRID units, like the reference; multiply by the cell pixel
    size for image coords."""

    def __init__(self, center_x: float, center_y: float, width: float,
                 height: float, predicted_class: int, confidence: float,
                 class_probabilities=None):
        self.center_x = float(center_x)
        self.center_y = float(center_y)
        self.width = float(width)
        self.height = float(height)
        self.predicted_class = int(predicted_class)
        self.confidence = float(confidence)
        self.class_probabilities = class_probabilities

    # reference getters
    def getCenterX(self):
        return self.center_x

    def getCenterY(self):
        return self.center_y

    def getWidth(self):
        return self.width

    def getHeight(self):
        return self.height

    def getPredictedClass(self):
        return self.predicted_class

    def getConfidence(self):
        return self.confidence

    def getTopLeftXY(self):
        return (self.center_x - self.width / 2,
                self.center_y - self.height / 2)

    def getBottomRightXY(self):
        return (self.center_x + self.width / 2,
                self.center_y + self.height / 2)

    def __repr__(self):
        return (f"DetectedObject(cls={self.predicted_class}, "
                f"conf={self.confidence:.3f}, xy=({self.center_x:.2f},"
                f"{self.center_y:.2f}), wh=({self.width:.2f},"
                f"{self.height:.2f}))")


class YoloUtils:
    """Detection decoding (reference:
    org/deeplearning4j/nn/layers/objdetect/YoloUtils —
    getPredictedObjects + NMS)."""

    @staticmethod
    def getPredictedObjects(layer: "Yolo2OutputLayer", network_output,
                            conf_threshold: float = 0.5,
                            nms_threshold: float = 0.4,
                            max_objects: int = 50):
        """Per-image lists of DetectedObject from raw [N,H,W,B*(5+C)]
        activations: sigmoid/exp decode -> OBJECTNESS-confidence filter
        (reference semantics: the threshold and
        ``DetectedObject.confidence`` are the objectness score, not
        objectness*classProb) -> greedy per-image NMS, batched through
        one jitted vmap of the XLA-safe non_max_suppression op."""
        import numpy as np

        x = jnp.asarray(network_output)
        n, h, w, d = x.shape
        b = len(layer.anchors)
        n_classes = d // b - 5
        if n_classes < 1 or d != b * (5 + n_classes):
            raise ValueError(
                f"output depth {d} is not B*(5+C) for B={b} anchors "
                f"(got C={n_classes}) — check the layer's anchors match "
                "the network")
        xy, wh, conf, cls_logits = layer._decode(x, n_classes)
        cls_prob = jax.nn.softmax(cls_logits, axis=-1)

        xyf = xy.reshape(n, -1, 2)
        whf = wh.reshape(n, -1, 2)
        scf = conf.reshape(n, -1)
        boxes = jnp.stack([xyf[..., 1] - whf[..., 1] / 2,   # y1
                           xyf[..., 0] - whf[..., 0] / 2,   # x1
                           xyf[..., 1] + whf[..., 1] / 2,   # y2
                           xyf[..., 0] + whf[..., 0] / 2],  # x2
                          axis=-1)                           # [N,HWB,4]
        nms = _batched_nms(max_objects, nms_threshold, conf_threshold)
        sels, counts = nms(boxes, scf)

        xy_n, wh_n = np.asarray(xyf), np.asarray(whf)
        score_n = np.asarray(scf)
        cls_n = np.asarray(jnp.argmax(cls_prob, axis=-1)).reshape(n, -1)
        prob_n = np.asarray(cls_prob).reshape(n, -1, n_classes)
        sels_n, counts_n = np.asarray(sels), np.asarray(counts)

        results = []
        for i in range(n):
            dets = []
            for j in sels_n[i][:int(counts_n[i])]:
                dets.append(DetectedObject(
                    xy_n[i, j, 0], xy_n[i, j, 1],
                    wh_n[i, j, 0], wh_n[i, j, 1],
                    int(cls_n[i, j]), float(score_n[i, j]),
                    prob_n[i, j].copy()))
            results.append(dets)
        return results
