"""NeuralNetConfiguration / MultiLayerConfiguration builders.

Reference: org/deeplearning4j/nn/conf/NeuralNetConfiguration.java
(Builder + ListBuilder) and MultiLayerConfiguration.java — fluent
builder, global defaults cloned into layers, `setInputType` driving nIn
inference and automatic InputPreProcessor insertion, and a guaranteed
JSON round-trip (SURVEY.md §2.18).

Differences by design: preprocessors are tagged strings (pure reshapes
resolved at trace time), and the canonical image layout is NHWC.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.common import serde
from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.learning.updaters import IUpdater, Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    Bidirectional, ConvolutionLayer, DenseLayer, EmbeddingLayer, Layer,
    LastTimeStep, LearnedSelfAttentionLayer, LSTM, RecurrentAttentionLayer,
    SimpleRnn, SubsamplingLayer, SelfAttentionLayer, Upsampling2D,
    ZeroPaddingLayer, LocalResponseNormalization, GravesLSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers_extra import (
    CapsuleLayer, CapsuleStrengthLayer, Convolution1D, Convolution3D,
    Cropping1D, Cropping2D, Cropping3D, GravesBidirectionalLSTM, GRU,
    LocallyConnected1D,
    LocallyConnected2D, MaskZeroLayer, PrimaryCapsules, SpaceToBatchLayer,
    SpaceToDepthLayer, Subsampling1DLayer, Subsampling3DLayer, Upsampling1D,
    Upsampling3D, ZeroPadding1DLayer, ZeroPadding3DLayer,
)

#: layers that consume image [N,H,W,C] input
_CNN2D_LAYERS = (ConvolutionLayer, SubsamplingLayer, Upsampling2D,
                 ZeroPaddingLayer, LocalResponseNormalization, Cropping2D,
                 SpaceToDepthLayer, SpaceToBatchLayer, LocallyConnected2D,
                 PrimaryCapsules)
#: layers that consume volumetric [N,D,H,W,C] input
_CNN3D_LAYERS = (Convolution3D, Subsampling3DLayer, Upsampling3D,
                 Cropping3D, ZeroPadding3DLayer)
#: layers that consume sequence [N,T,F] input
_RNN_LAYERS = (LSTM, SimpleRnn, GravesLSTM, GRU, GravesBidirectionalLSTM,
               SelfAttentionLayer,
               LastTimeStep, Bidirectional, LearnedSelfAttentionLayer,
               RecurrentAttentionLayer, RnnOutputLayer, Convolution1D,
               Subsampling1DLayer, Upsampling1D, Cropping1D,
               ZeroPadding1DLayer, LocallyConnected1D, MaskZeroLayer,
               CapsuleLayer, CapsuleStrengthLayer)


@serializable
@dataclasses.dataclass
class MultiLayerConfiguration:
    """Built, fully-resolved network config (all nIn known, preprocessors
    placed). Reference: MultiLayerConfiguration.java."""

    layers: List[Any] = dataclasses.field(default_factory=list)
    seed: int = 12345
    updater: Any = dataclasses.field(default_factory=lambda: Sgd())
    weight_init: str = "xavier"
    l1: float = 0.0
    l2: float = 0.0
    dtype: str = "float32"
    #: mixed-precision policy: None (legacy single-dtype mode driven by
    #: ``dtype``), a preset name ("float32" / "mixed_bfloat16" /
    #: "mixed_float16"), or a nn.precision.PrecisionPolicy
    precision: Optional[Any] = None
    input_type: Optional[InputType] = None
    #: layer index -> preprocessor tag ("flatten" | "to_conv:H,W,C")
    preprocessors: Dict = dataclasses.field(default_factory=dict)
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    tbptt_fwd_length: int = 0
    tbptt_back_length: int = 0

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        cfg = serde.from_json(s)
        cfg.preprocessors = {int(k): v for k, v in cfg.preprocessors.items()}
        return cfg

    def __post_init__(self):
        self.preprocessors = {int(k): v for k, v in self.preprocessors.items()}


class NeuralNetConfiguration:
    """Entry point: NeuralNetConfiguration.builder()... (reference API)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._seed = 12345
        self._updater: IUpdater = Sgd()
        self._weight_init = "xavier"
        self._l1 = 0.0
        self._l2 = 0.0
        self._dtype = "float32"
        self._precision = None
        self._dropout = None
        self._activation = None
        self._grad_norm = None
        self._grad_norm_threshold = 1.0

    # fluent setters (reference naming kept, camelCase)
    def seed(self, s: int) -> "Builder":
        self._seed = int(s)
        return self

    def updater(self, u: IUpdater) -> "Builder":
        self._updater = u
        return self

    def weightInit(self, w) -> "Builder":
        self._weight_init = w.value if hasattr(w, "value") else str(w)
        return self

    def activation(self, a) -> "Builder":
        self._activation = a.value if hasattr(a, "value") else str(a)
        return self

    def l1(self, v: float) -> "Builder":
        self._l1 = float(v)
        return self

    def l2(self, v: float) -> "Builder":
        self._l2 = float(v)
        return self

    def dataType(self, dt) -> "Builder":
        self._dtype = dt.value if hasattr(dt, "value") else str(dt)
        return self

    def precision(self, policy) -> "Builder":
        """Mixed-precision policy: "float32", "mixed_bfloat16",
        "mixed_float16", or a PrecisionPolicy (nn/precision.py).
        Orthogonal to dataType(): a mixed policy keeps MASTER params in
        its param_dtype (fp32) and only the per-step compute drops to
        bf16/f16."""
        self._precision = policy
        return self

    def dropOut(self, keep: float) -> "Builder":
        # reference semantics: dropOut(x) with x = retain probability.
        # We store DROP rate to match our ops; convert here.
        self._dropout = 1.0 - float(keep) if keep > 0 else None
        return self

    def gradientNormalization(self, mode: str, threshold: float = 1.0) -> "Builder":
        self._grad_norm = mode
        self._grad_norm_threshold = threshold
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self)


class ListBuilder:
    """reference: NeuralNetConfiguration.ListBuilder."""

    def __init__(self, parent: Builder):
        self._p = parent
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = None   # None = infer from tBPTT lengths
        self._tbptt_fwd = 0
        self._tbptt_back = 0

    def layer(self, *args) -> "ListBuilder":
        """layer(conf) or layer(index, conf) — both reference forms."""
        conf = args[-1]
        self._layers.append(conf)
        return self

    def setInputType(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    # -- truncated BPTT (reference: ListBuilder#backpropType +
    # tBPTTForwardLength/tBPTTBackwardLength, SURVEY.md §5) -------------
    def backpropType(self, bp_type: str) -> "ListBuilder":
        """'Standard' or 'TruncatedBPTT' (tBPTT needs tBPTTLength too)."""
        self._backprop_type = str(bp_type)
        return self

    def tBPTTForwardLength(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = int(n)
        return self

    def tBPTTBackwardLength(self, n: int) -> "ListBuilder":
        self._tbptt_back = int(n)
        return self

    def tBPTTLength(self, n: int) -> "ListBuilder":
        return self.tBPTTForwardLength(n).tBPTTBackwardLength(n)

    def inputType(self, it: InputType) -> "ListBuilder":
        return self.setInputType(it)

    def build(self) -> MultiLayerConfiguration:
        """Resolve defaults, infer nIn per layer, insert preprocessors.

        Mirrors MultiLayerConfiguration#build + setInputType logic:
        walk layers tracking the current InputType; when a layer needs a
        different representation, record a reshape preprocessor.
        """
        p = self._p
        layers = self._layers
        if not layers:
            raise ValueError("No layers added")
        preprocessors: Dict[int, str] = {}
        it = self._input_type

        for i, layer in enumerate(layers):
            # inherit global defaults (reference: config cloning); for
            # Bidirectional the wrapped layer holds the real conf
            targets = [layer] + ([layer.layer]
                                 if isinstance(layer, Bidirectional) else [])
            for lt in targets:
                if lt.activation is None and p._activation is not None:
                    lt.activation = p._activation
                if lt.weight_init is None:
                    lt.weight_init = p._weight_init
                if lt.l1 is None:
                    lt.l1 = p._l1
                if lt.l2 is None:
                    lt.l2 = p._l2
                if lt.dropout is None and p._dropout is not None:
                    lt.dropout = p._dropout

            if it is None:
                continue  # no shape inference possible; user set n_in

            # representation changes -> preprocessors
            if isinstance(layer, _CNN2D_LAYERS) \
                    and not isinstance(layer, DenseLayer):
                if it.kind == "convolutionalFlat":
                    preprocessors[i] = f"to_conv:{it.height},{it.width},{it.channels}"
                    it = InputType.convolutional(it.height, it.width, it.channels)
                elif it.kind != "convolutional":
                    raise ValueError(
                        f"Layer {i} ({type(layer).__name__}) needs image input, got {it.kind}")
            elif isinstance(layer, _CNN3D_LAYERS):
                if it.kind != "convolutional3d":
                    raise ValueError(
                        f"Layer {i} ({type(layer).__name__}) needs 3D image input, got {it.kind}")
            elif isinstance(layer, _RNN_LAYERS):
                if it.kind not in ("recurrent",):
                    raise ValueError(
                        f"Layer {i} ({type(layer).__name__}) needs recurrent input, got {it.kind}")
            elif isinstance(layer, DenseLayer):  # includes OutputLayer
                if it.kind in ("convolutional", "convolutional3d"):
                    preprocessors[i] = "flatten"
                    it = InputType.feedForward(it.flat_size())
                elif it.kind == "convolutionalFlat":
                    it = InputType.feedForward(it.flat_size())

            # nIn inference (unwrap LastTimeStep/Bidirectional to reach
            # the recurrent layer that actually holds n_in)
            target = layer
            # unwrap wrapper layers (LastTimeStep/Bidirectional/MaskZero/
            # Frozen*) to reach the layer that actually holds n_in
            while True:
                if isinstance(target, LastTimeStep):
                    target = target.underlying
                elif isinstance(target.__class__.__dict__.get("n_in"),
                                property) or not hasattr(target, "n_in"):
                    inner = getattr(target, "layer", None)
                    if isinstance(inner, Layer) and hasattr(inner, "n_in"):
                        target = inner
                    else:
                        break
                else:
                    break
            if hasattr(target, "n_in") and getattr(target, "n_in", 0) in (0, None) \
                    and not isinstance(target, EmbeddingLayer):
                if it.kind in ("convolutional", "convolutional3d"):
                    target.n_in = it.channels
                else:
                    target.n_in = it.size
            # attention n_out default
            if isinstance(layer, (SelfAttentionLayer,
                                  LearnedSelfAttentionLayer,
                                  RecurrentAttentionLayer)) \
                    and layer.n_out == 0:
                layer.n_out = layer.n_in

            it = layer.output_type(it)

        # tBPTT resolution: explicit backpropType wins; setting a length
        # without backpropType implies TruncatedBPTT; TruncatedBPTT with
        # no length uses the reference default of 20.
        if self._backprop_type == "Standard":
            tbptt_fwd = 0
        elif self._backprop_type == "TruncatedBPTT":
            tbptt_fwd = self._tbptt_fwd or 20
        else:
            tbptt_fwd = self._tbptt_fwd
        tbptt_back = self._tbptt_back or tbptt_fwd
        if tbptt_fwd and tbptt_back != tbptt_fwd:
            import warnings
            warnings.warn(
                "tBPTTBackwardLength != tBPTTForwardLength is not supported "
                "on the compiled tBPTT path (backward length follows the "
                f"segment length {tbptt_fwd}); configured {tbptt_back} is "
                "recorded but has no effect", stacklevel=2)

        return MultiLayerConfiguration(
            layers=layers,
            seed=p._seed,
            updater=p._updater,
            weight_init=p._weight_init,
            l1=p._l1,
            l2=p._l2,
            dtype=p._dtype,
            precision=p._precision,
            input_type=self._input_type,
            preprocessors=preprocessors,
            gradient_normalization=p._grad_norm,
            gradient_normalization_threshold=p._grad_norm_threshold,
            tbptt_fwd_length=tbptt_fwd,
            tbptt_back_length=tbptt_back,
        )


def apply_preprocessor(tag: str, x):
    """Resolve a preprocessor tag to a reshape (trace-time, free on TPU)."""
    if tag == "flatten":
        return x.reshape(x.shape[0], -1)
    if tag.startswith("to_conv:"):
        h, w, c = (int(v) for v in tag.split(":", 1)[1].split(","))
        return x.reshape(x.shape[0], h, w, c)
    if tag == "to_rnn":
        return x
    raise ValueError(f"Unknown preprocessor: {tag}")
