"""Extended layer set — the rest of the reference's conf/layers/** tree.

Reference (SURVEY.md §2.20): org/deeplearning4j/nn/conf/layers/
{Convolution1DLayer, Convolution3D, Deconvolution2D,
DepthwiseConvolution2D, Subsampling1DLayer, Subsampling3DLayer,
Upsampling1D, Upsampling3D, Cropping1D/2D/3D (convolutional/),
ZeroPadding1DLayer, ZeroPadding3DLayer, SpaceToDepthLayer,
SpaceToBatchLayer, LocallyConnected1D, LocallyConnected2D, PReLULayer,
misc/ElementWiseMultiplicationLayer, misc/RepeatVector,
misc/FrozenLayerWithBackprop, util/MaskLayer, util/MaskZeroLayer,
CenterLossOutputLayer, CapsuleLayer, PrimaryCapsules,
CapsuleStrengthLayer, GRU (legacy conf)}.

Same functional contract as layers.py: each layer is a serializable
dataclass with pure init_params/apply, composed into ONE jit-compiled
XLA step by the network front-ends. Layout conventions: images NHWC,
volumes NDHWC, sequences NTF ([N,T,F] — the reference's 1D-CNN layers
operate on RNN-format input too).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable, _tuplify
from deeplearning4j_tpu.loss import LossFunction, compute_loss
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, Layer, OutputLayer, _act, _conv_out,
)
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights
from deeplearning4j_tpu.ops import nn as nnops
from deeplearning4j_tpu.ops import shape as shapeops


# ----------------------------------------------------------------------
# recurrent: GRU
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class GRU(Layer):
    """GRU layer over the fused ``gru_layer`` scan (reference: the
    legacy conf/layers/GRU; gate order r,z,n)."""

    n_in: int = 0
    n_out: int = 0
    #: separate recurrent bias on the h-projection (Keras reset_after
    #: checkpoint parity; adds param "Rb")
    recurrent_bias: bool = False

    is_recurrent = True

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        k1, k2 = jax.random.split(key)
        h = self.n_out
        w = init_weights(self.weight_init or WeightInit.XAVIER, k1,
                         (self.n_in, 3 * h), self.n_in, 3 * h, dtype)
        rw = init_weights(self.weight_init or WeightInit.XAVIER, k2,
                          (h, 3 * h), h, 3 * h, dtype)
        p = {"W": w, "RW": rw, "b": jnp.zeros((3 * h,), dtype)}
        if self.recurrent_bias:
            p["Rb"] = jnp.zeros((3 * h,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        ys, _ = nnops.gru_layer(x, params["W"], params["RW"], params["b"],
                                rb=params.get("Rb"))
        return ys, state

    def init_carry(self, batch, dtype):
        return jnp.zeros((batch, self.n_out), dtype)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        ys, new_carry = nnops.gru_layer(
            x, params["W"], params["RW"], params["b"], h0=carry,
            rb=params.get("Rb"))
        return ys, state, new_carry


# ----------------------------------------------------------------------
# 1D convolution family (sequence input, NTF)
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class Convolution1D(Layer):
    """1D conv on [N,T,F] (reference: conf/layers/Convolution1DLayer —
    operates on RNN-format input)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "Truncate"
    dilation: int = 1
    has_bias: bool = True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t and t > 0:
            t = _conv_out(t, self.kernel_size, self.stride,
                          self.convolution_mode, self.padding, self.dilation)
        return InputType.recurrent(self.n_out, t)

    def init_params(self, key, it, dtype) -> dict:
        k = self.kernel_size
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (k, self.n_in, self.n_out), k * self.n_in,
                         k * self.n_out, dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        pad = "SAME" if self.convolution_mode == "Same" else self.padding
        out = nnops.conv1d(x, params["W"], params.get("b"),
                           stride=self.stride, padding=pad,
                           dilation=self.dilation)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class Subsampling1DLayer(Layer):
    """1D pooling on [N,T,F] (reference: conf/layers/Subsampling1DLayer)."""

    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "Truncate"
    pnorm: int = 2

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t and t > 0:
            t = _conv_out(t, self.kernel_size, self.stride,
                          self.convolution_mode, self.padding)
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, train, rng):
        pad = "SAME" if self.convolution_mode == "Same" else (
            "VALID" if self.padding == 0 else self.padding)
        pt = self.pooling_type.lower()
        if pt == "max":
            return nnops.maxpool1d(x, self.kernel_size, self.stride, pad), state
        if pt == "avg":
            return nnops.avgpool1d(x, self.kernel_size, self.stride, pad), state
        if pt == "pnorm":
            return nnops.pnormpool1d(x, self.kernel_size, self.stride, pad,
                                     self.pnorm), state
        return nnops.sumpool1d(x, self.kernel_size, self.stride, pad), state


@serializable
@dataclasses.dataclass
class Upsampling1D(Layer):
    """Repeat each timestep `size` times (reference: conf/layers/Upsampling1D)."""

    size: int = 2

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        return InputType.recurrent(it.size, t * self.size if t and t > 0 else t)

    def apply(self, params, state, x, train, rng):
        return jnp.repeat(x, self.size, axis=1), state


@serializable
@dataclasses.dataclass
class Cropping1D(Layer):
    """Crop timesteps from both ends (reference: convolutional/Cropping1D)."""

    crop: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        c = self.crop
        self.crop = (c, c) if isinstance(c, int) else _tuplify(c)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t and t > 0:
            t = t - self.crop[0] - self.crop[1]
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, train, rng):
        t = x.shape[1]
        return x[:, self.crop[0]:t - self.crop[1], :], state


@serializable
@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    """Pad timesteps (reference: conf/layers/ZeroPadding1DLayer)."""

    pad: Tuple[int, int] = (1, 1)

    def __post_init__(self):
        p = self.pad
        self.pad = (p, p) if isinstance(p, int) else _tuplify(p)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t and t > 0:
            t = t + self.pad[0] + self.pad[1]
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, train, rng):
        return jnp.pad(x, ((0, 0), self.pad, (0, 0))), state


# ----------------------------------------------------------------------
# 2D convolution family extensions
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed conv (reference: conf/layers/Deconvolution2D)."""

    def output_type(self, it: InputType) -> InputType:
        if self.convolution_mode == "Same":
            h = it.height * self.stride[0]
            w = it.width * self.stride[1]
        else:
            h = self.stride[0] * (it.height - 1) + self.kernel_size[0] \
                - 2 * self.padding[0]
            w = self.stride[1] * (it.width - 1) + self.kernel_size[1] \
                - 2 * self.padding[1]
        return InputType.convolutional(h, w, self.n_out)

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        pad = "SAME" if self.convolution_mode == "Same" else self.padding
        out = nnops.deconv2d(x, params["W"], params.get("b"),
                             strides=self.stride, padding=pad)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    """Depthwise conv (reference: conf/layers/DepthwiseConvolution2D).
    n_out = n_in * depth_multiplier."""

    depth_multiplier: int = 1

    def output_type(self, it: InputType) -> InputType:
        base = super().output_type(it)
        return InputType.convolutional(base.height, base.width,
                                       self.n_in * self.depth_multiplier)

    def init_params(self, key, it, dtype) -> dict:
        kh, kw = self.kernel_size
        fan = kh * kw * self.n_in
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (kh, kw, self.n_in, self.depth_multiplier),
                         fan, fan * self.depth_multiplier, dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_in * self.depth_multiplier,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        out = nnops.depthwise_conv2d(x, params["W"], params.get("b"),
                                     strides=self.stride,
                                     padding=self._pad_arg(),
                                     dilation=self.dilation)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class Cropping2D(Layer):
    """Crop H/W (reference: convolutional/Cropping2D)."""

    crop: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top,bottom,left,right

    def __post_init__(self):
        c = _tuplify(self.crop)
        if isinstance(c, int):
            c = (c, c, c, c)
        elif len(c) == 2:
            c = (c[0], c[0], c[1], c[1])
        self.crop = c

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        t, b, l, r = self.crop
        return InputType.convolutional(it.height - t - b, it.width - l - r,
                                       it.channels)

    def apply(self, params, state, x, train, rng):
        t, b, l, r = self.crop
        return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :], state


@serializable
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """(reference: conf/layers/SpaceToDepthLayer)."""

    block_size: int = 2

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        bs = self.block_size
        return InputType.convolutional(it.height // bs, it.width // bs,
                                       it.channels * bs * bs)

    def apply(self, params, state, x, train, rng):
        return shapeops.space_to_depth(x, self.block_size), state


@serializable
@dataclasses.dataclass
class SpaceToBatchLayer(Layer):
    """(reference: conf/layers/SpaceToBatchLayer)."""

    block_size: int = 2
    padding: Tuple[Tuple[int, int], Tuple[int, int]] = ((0, 0), (0, 0))

    def __post_init__(self):
        p = _tuplify(self.padding)
        self.padding = tuple(_tuplify(v) for v in p)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        bs = self.block_size
        (pt, pb), (pl, pr) = self.padding
        return InputType.convolutional((it.height + pt + pb) // bs,
                                       (it.width + pl + pr) // bs,
                                       it.channels)

    def apply(self, params, state, x, train, rng):
        return shapeops.space_to_batch(
            x, (self.block_size, self.block_size), list(self.padding)), state


# ----------------------------------------------------------------------
# 3D convolution family (volumes, NDHWC)
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class Convolution3D(Layer):
    """3D conv on [N,D,H,W,C] (reference: conf/layers/Convolution3D;
    reference layout NCDHW — here NDHWC, the TPU-preferred layout)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    padding: Tuple[int, int, int] = (0, 0, 0)
    convolution_mode: str = "Truncate"
    dilation: Tuple[int, int, int] = (1, 1, 1)
    has_bias: bool = True

    def __post_init__(self):
        for f in ("kernel_size", "stride", "padding", "dilation"):
            v = getattr(self, f)
            setattr(self, f, (v, v, v) if isinstance(v, int) else _tuplify(v))

    def output_type(self, it: InputType) -> InputType:
        dims = [_conv_out(s, self.kernel_size[i], self.stride[i],
                          self.convolution_mode, self.padding[i],
                          self.dilation[i])
                for i, s in enumerate((it.depth, it.height, it.width))]
        return InputType.convolutional3D(dims[0], dims[1], dims[2], self.n_out)

    def init_params(self, key, it, dtype) -> dict:
        kd, kh, kw = self.kernel_size
        fan_in = kd * kh * kw * self.n_in
        fan_out = kd * kh * kw * self.n_out
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (kd, kh, kw, self.n_in, self.n_out), fan_in, fan_out,
                         dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        pad = "SAME" if self.convolution_mode == "Same" else self.padding
        out = nnops.conv3d(x, params["W"], params.get("b"),
                           strides=self.stride, padding=pad,
                           dilation=self.dilation)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class Deconvolution3D(Convolution3D):
    """3D transposed conv on [N,D,H,W,C] (reference: conf/layers/
    Deconvolution3D; NCDHW there, NDHWC here)."""

    def __post_init__(self):
        super().__post_init__()
        if tuple(self.dilation) != (1, 1, 1):
            raise ValueError(
                "Deconvolution3D does not support dilation != (1,1,1) "
                "(the transposed-conv lowering has no dilated form here); "
                f"got {self.dilation}")

    def output_type(self, it: InputType) -> InputType:
        dims = []
        for i, s in enumerate((it.depth, it.height, it.width)):
            if self.convolution_mode == "Same":
                dims.append(s * self.stride[i])
            else:
                dims.append(self.stride[i] * (s - 1) + self.kernel_size[i]
                            - 2 * self.padding[i])
        return InputType.convolutional3D(dims[0], dims[1], dims[2],
                                         self.n_out)

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        from deeplearning4j_tpu.ops.declarable_tail import deconv3d
        if self.convolution_mode == "Same":
            pad = "SAME"
        else:
            # reference semantics out = s(in-1)+k-2p; conv_transpose
            # pads the stride-dilated input directly, so low = high =
            # k-1-p (same mapping as deconv2d, ops/nn.py)
            pad = [(k - 1 - p, k - 1 - p)
                   for k, p in zip(self.kernel_size, self.padding)]
        out = deconv3d(x, params["W"], strides=self.stride, padding=pad)
        if self.has_bias:
            out = out + params["b"]
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class Subsampling3DLayer(Layer):
    """3D pooling (reference: conf/layers/Subsampling3DLayer)."""

    pooling_type: str = "max"
    kernel_size: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    padding: Tuple[int, int, int] = (0, 0, 0)
    convolution_mode: str = "Truncate"

    def __post_init__(self):
        for f in ("kernel_size", "stride", "padding"):
            v = getattr(self, f)
            setattr(self, f, (v, v, v) if isinstance(v, int) else _tuplify(v))

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        dims = [_conv_out(s, self.kernel_size[i], self.stride[i],
                          self.convolution_mode, self.padding[i])
                for i, s in enumerate((it.depth, it.height, it.width))]
        return InputType.convolutional3D(dims[0], dims[1], dims[2], it.channels)

    def apply(self, params, state, x, train, rng):
        pad = "SAME" if self.convolution_mode == "Same" else (
            "VALID" if self.padding == (0, 0, 0) else self.padding)
        if self.pooling_type.lower() == "avg":
            return nnops.avgpool3d(x, self.kernel_size, self.stride, pad), state
        return nnops.maxpool3d(x, self.kernel_size, self.stride, pad), state


@serializable
@dataclasses.dataclass
class Upsampling3D(Layer):
    """(reference: conf/layers/Upsampling3D)."""

    size: int = 2

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        s = self.size
        return InputType.convolutional3D(it.depth * s, it.height * s,
                                         it.width * s, it.channels)

    def apply(self, params, state, x, train, rng):
        s = self.size
        x = jnp.repeat(x, s, axis=1)
        x = jnp.repeat(x, s, axis=2)
        return jnp.repeat(x, s, axis=3), state


@serializable
@dataclasses.dataclass
class Cropping3D(Layer):
    """(reference: convolutional/Cropping3D)."""

    crop: Tuple[int, int, int, int, int, int] = (0, 0, 0, 0, 0, 0)

    def __post_init__(self):
        c = _tuplify(self.crop)
        if isinstance(c, int):
            c = (c,) * 6
        elif len(c) == 3:
            c = (c[0], c[0], c[1], c[1], c[2], c[2])
        self.crop = c

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        c = self.crop
        return InputType.convolutional3D(it.depth - c[0] - c[1],
                                         it.height - c[2] - c[3],
                                         it.width - c[4] - c[5], it.channels)

    def apply(self, params, state, x, train, rng):
        c = self.crop
        return x[:, c[0]:x.shape[1] - c[1], c[2]:x.shape[2] - c[3],
                 c[4]:x.shape[3] - c[5], :], state


@serializable
@dataclasses.dataclass
class ZeroPadding3DLayer(Layer):
    """(reference: conf/layers/ZeroPadding3DLayer)."""

    pad: Tuple[int, int, int] = (1, 1, 1)

    def __post_init__(self):
        p = _tuplify(self.pad)
        self.pad = (p, p, p) if isinstance(p, int) else p

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        p = self.pad
        return InputType.convolutional3D(it.depth + 2 * p[0],
                                         it.height + 2 * p[1],
                                         it.width + 2 * p[2], it.channels)

    def apply(self, params, state, x, train, rng):
        p = self.pad
        return jnp.pad(x, ((0, 0), (p[0], p[0]), (p[1], p[1]),
                           (p[2], p[2]), (0, 0))), state


# ----------------------------------------------------------------------
# locally connected (unshared weights)
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class LocallyConnected2D(Layer):
    """Unshared-weight 2D conv (reference: conf/layers/LocallyConnected2D,
    a SameDiff layer in the reference — here a first-class layer whose
    im2col+einsum stays on the MXU)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (1, 1)
    has_bias: bool = True
    #: resolved at init from the input type
    input_size: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        self.kernel_size = _tuplify(self.kernel_size)
        self.stride = _tuplify(self.stride)
        self.input_size = _tuplify(self.input_size)

    def _out_hw(self, it: InputType):
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0],
                      "Truncate", 0)
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1],
                      "Truncate", 0)
        return h, w

    def output_type(self, it: InputType) -> InputType:
        h, w = self._out_hw(it)
        return InputType.convolutional(h, w, self.n_out)

    def init_params(self, key, it, dtype) -> dict:
        self.input_size = (it.height, it.width)
        oh, ow = self._out_hw(it)
        kh, kw = self.kernel_size
        kc = kh * kw * self.n_in
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (oh * ow, kc, self.n_out), kc, self.n_out, dtype)
        p = {"W": w}
        if self.has_bias:
            # per-position bias, matching Keras LocallyConnected2D
            p["b"] = jnp.zeros((oh, ow, self.n_out), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        out = nnops.locally_connected2d(x, params["W"], params.get("b"),
                                        self.kernel_size, self.stride, "VALID")
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class LocallyConnected1D(Layer):
    """Unshared-weight 1D conv (reference: conf/layers/LocallyConnected1D)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 2
    stride: int = 1
    has_bias: bool = True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t and t > 0:
            t = _conv_out(t, self.kernel_size, self.stride, "Truncate", 0)
        return InputType.recurrent(self.n_out, t)

    def init_params(self, key, it, dtype) -> dict:
        t = it.timeseries_length
        if not t or t <= 0:
            raise ValueError("LocallyConnected1D needs a fixed sequence length")
        ot = _conv_out(t, self.kernel_size, self.stride, "Truncate", 0)
        kc = self.kernel_size * self.n_in
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (ot, kc, self.n_out), kc, self.n_out, dtype)
        p = {"W": w}
        if self.has_bias:
            # per-position bias, matching Keras LocallyConnected1D
            p["b"] = jnp.zeros((ot, self.n_out), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        out = nnops.locally_connected1d(x, params["W"], params.get("b"),
                                        self.kernel_size, self.stride, "VALID")
        return _act(self.activation or "identity").fn(out), state


# ----------------------------------------------------------------------
# misc parameterized layers
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class PReLULayer(Layer):
    """Learned per-feature leaky slope (reference: conf/layers/PReLULayer)."""

    n_in: int = 0  # feature width (inferred)

    def output_type(self, it: InputType) -> InputType:
        return it

    def init_params(self, key, it, dtype) -> dict:
        n = it.channels if it.kind in ("convolutional", "convolutional3d") \
            else it.size
        self.n_in = self.n_in or n
        return {"alpha": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, state, x, train, rng):
        from deeplearning4j_tpu.ops.transforms import prelu
        return prelu(x, params["alpha"]), state


@serializable
@dataclasses.dataclass
class ElementWiseMultiplicationLayer(Layer):
    """out = act(x * w + b), learned elementwise scale (reference:
    conf/layers/misc/ElementWiseMultiplicationLayer)."""

    n_in: int = 0
    n_out: int = 0  # == n_in; kept for config parity

    def output_type(self, it: InputType) -> InputType:
        return it

    def init_params(self, key, it, dtype) -> dict:
        n = self.n_in or it.size
        self.n_in = self.n_out = n
        return {"W": jnp.ones((n,), dtype), "b": jnp.zeros((n,), dtype)}

    def apply(self, params, state, x, train, rng):
        return _act(self.activation or "identity").fn(
            x * params["W"] + params["b"]), state


@serializable
@dataclasses.dataclass
class RepeatVector(Layer):
    """[N,F] -> [N,n,F] (reference: conf/layers/misc/RepeatVector)."""

    n: int = 1

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(it.size, self.n)

    def apply(self, params, state, x, train, rng):
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], self.n, x.shape[-1])), state


@serializable
@dataclasses.dataclass
class MaskLayer(Layer):
    """Pass-through (reference: conf/layers/util/MaskLayer — zeroes
    activations at masked timesteps; in this framework masks are carried
    alongside activations and applied in the loss, so forward is
    identity. Kept for config/import parity)."""

    def has_params(self):
        return False

    def apply(self, params, state, x, train, rng):
        return x, state


@serializable
@dataclasses.dataclass
class MaskZeroLayer(Layer):
    """Wrap a recurrent layer; timesteps whose input features all equal
    ``mask_value`` produce zero output (reference:
    conf/layers/util/MaskZeroLayer)."""

    layer: Optional[Layer] = None
    mask_value: float = 0.0

    @property
    def is_recurrent(self):
        return self.layer is not None and self.layer.is_recurrent

    @property
    def n_in(self):
        return self.layer.n_in

    @n_in.setter
    def n_in(self, v):
        self.layer.n_in = v

    @property
    def n_out(self):
        return self.layer.n_out

    def has_params(self):
        return self.layer.has_params()

    def output_type(self, it: InputType) -> InputType:
        return self.layer.output_type(it)

    def init_params(self, key, it, dtype) -> dict:
        return self.layer.init_params(key, it, dtype)

    def init_state(self, it, dtype) -> dict:
        return self.layer.init_state(it, dtype)

    def apply(self, params, state, x, train, rng):
        mask = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        out, st = self.layer.apply(params, state, x, train, rng)
        return out * mask.astype(out.dtype), st


# ----------------------------------------------------------------------
# CenterLoss output head
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax head + center loss (reference:
    conf/layers/CenterLossOutputLayer; Wen et al. 2016).

    Loss = CE + (lambda/2)·||x − c_y||². Design deviation: the reference
    updates centers with a dedicated alpha running average outside the
    optimizer; here centers are parameters whose gradient
    (lambda·(c_y − x)) flows through the shared updater — same fixed
    point, one compiled step.
    """

    alpha: float = 0.05     # kept for config parity
    lambda_: float = 2e-4

    def init_params(self, key, it, dtype) -> dict:
        p = super().init_params(key, it, dtype)
        p["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def loss_value(self, params, state, x, labels, mask=None):
        base = super().loss_value(params, state, x, labels, mask)
        # labels one-hot [N, n_out] -> per-row center [N, n_in]
        cy = labels @ params["centers"]
        d = x - cy
        center = jnp.mean(jnp.sum(d * d, axis=-1))
        return base + 0.5 * self.lambda_ * center


# ----------------------------------------------------------------------
# Capsule network layers (reference: CapsuleLayer, PrimaryCapsules,
# CapsuleStrengthLayer — Sabour et al. 2017 dynamic routing)
# ----------------------------------------------------------------------
def _squash(s, axis=-1, eps=1e-8):
    n2 = jnp.sum(s * s, axis=axis, keepdims=True)
    return (n2 / (1.0 + n2)) * s / jnp.sqrt(n2 + eps)


@serializable
@dataclasses.dataclass
class PrimaryCapsules(Layer):
    """Conv -> capsule reshape + squash (reference: conf/layers/
    PrimaryCapsules). Output: recurrent [N, n_caps, capsule_dim]."""

    n_in: int = 0
    capsules: int = 0            # inferred from conv geometry if 0
    capsule_dimensions: int = 8
    channels: int = 32           # conv output channels per capsule dim
    kernel_size: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)

    def __post_init__(self):
        self.kernel_size = _tuplify(self.kernel_size)
        self.stride = _tuplify(self.stride)

    def _conv_geom(self, it: InputType):
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0],
                      "Truncate", 0)
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1],
                      "Truncate", 0)
        return h, w

    def output_type(self, it: InputType) -> InputType:
        h, w = self._conv_geom(it)
        caps = self.capsules or h * w * self.channels
        return InputType.recurrent(self.capsule_dimensions, caps)

    def init_params(self, key, it, dtype) -> dict:
        kh, kw = self.kernel_size
        c_out = self.channels * self.capsule_dimensions
        fan_in = kh * kw * self.n_in
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (kh, kw, self.n_in, c_out), fan_in,
                         kh * kw * c_out, dtype)
        return {"W": w, "b": jnp.zeros((c_out,), dtype)}

    def apply(self, params, state, x, train, rng):
        out = nnops.conv2d(x, params["W"], params["b"],
                           strides=self.stride, padding=self.padding_arg())
        n = out.shape[0]
        out = out.reshape(n, -1, self.capsule_dimensions)
        return _squash(out), state

    def padding_arg(self):
        return (0, 0)


@serializable
@dataclasses.dataclass
class CapsuleLayer(Layer):
    """Dynamic-routing capsule layer (reference: conf/layers/CapsuleLayer).

    Input [N, in_caps, in_dim] -> output [N, capsules, capsule_dim].
    Routing runs a fixed `routings` iterations — static control flow,
    so the whole routing unrolls into one XLA program.
    """

    n_in: int = 0                # input capsule dim (inferred)
    input_capsules: int = 0      # inferred
    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.capsule_dimensions, self.capsules)

    def init_params(self, key, it, dtype) -> dict:
        in_caps = self.input_capsules or max(it.timeseries_length, 1)
        in_dim = self.n_in or it.size
        self.input_capsules, self.n_in = in_caps, in_dim
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (in_caps, in_dim, self.capsules *
                          self.capsule_dimensions),
                         in_dim, self.capsules * self.capsule_dimensions,
                         dtype)
        return {"W": w}

    def apply(self, params, state, x, train, rng):
        n, in_caps, _ = x.shape
        oc, od = self.capsules, self.capsule_dimensions
        # predictions u_hat: [N, in_caps, out_caps, out_dim]
        u_hat = jnp.einsum("nid,ido->nio", x, params["W"]) \
            .reshape(n, in_caps, oc, od)
        b = jnp.zeros((n, in_caps, oc), x.dtype)
        v = None
        for _ in range(self.routings):
            c = jax.nn.softmax(b, axis=2)                  # route weights
            s = jnp.einsum("nio,niod->nod", c, u_hat)      # weighted sum
            v = _squash(s)                                 # [N, oc, od]
            b = b + jnp.einsum("niod,nod->nio", u_hat, v)  # agreement
        return v, state


@serializable
@dataclasses.dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule norms [N, caps, dim] -> [N, caps] (reference:
    conf/layers/CapsuleStrengthLayer)."""

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(max(it.timeseries_length, 1))

    def apply(self, params, state, x, train, rng):
        return jnp.sqrt(jnp.sum(x * x, axis=-1) + 1e-12), state


@serializable
@dataclasses.dataclass
class GravesBidirectionalLSTM(Layer):
    """Two independent LSTMs over both directions, concatenated
    (reference: conf/layers/GravesBidirectionalLSTM — predates the
    generic Bidirectional wrapper; kept as a first-class config for
    checkpoint/config parity; delegates to Bidirectional(LSTM))."""

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0

    is_recurrent = True

    def _delegate(self):
        from deeplearning4j_tpu.nn.conf.layers import Bidirectional, LSTM
        return Bidirectional(layer=LSTM(
            n_in=self.n_in, n_out=self.n_out,
            forget_gate_bias_init=self.forget_gate_bias_init,
            activation=self.activation, weight_init=self.weight_init,
            dropout=self.dropout, l1=self.l1, l2=self.l2),
            mode="CONCAT")

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(2 * self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        return self._delegate().init_params(key, it, dtype)

    def apply(self, params, state, x, train, rng):
        return self._delegate().apply(params, state, x, train, rng)

    def init_carry(self, batch, dtype):
        raise NotImplementedError(
            "rnnTimeStep is not supported for GravesBidirectionalLSTM "
            "(reference behavior: requires the full sequence)")


# ----------------------------------------------------------------------
# structural layers (Keras import parity: Permute / Reshape)
# ----------------------------------------------------------------------
@dataclasses.dataclass
class LambdaLayer(Layer):
    """User-defined stateless layer from a jax-traceable function
    (reference: SameDiffLambdaLayer — defineLayer over SDVariables;
    here the function is plain jax, traced into the same compiled
    step as everything else).

    ``fn(x) -> y`` must be pure/traceable. ``output_type_fn``
    (InputType -> InputType) defaults to shape-preserving. NOT
    JSON-serializable (a function has no portable config) — same
    restriction the reference's lambda layers have; model serde of a
    network containing one raises at to_json()."""

    fn: Any = None
    output_type_fn: Any = None

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return self.output_type_fn(it) if self.output_type_fn else it

    def apply(self, params, state, x, train, rng):
        if self.fn is None:
            raise ValueError("LambdaLayer needs fn=<jax-pure function>")
        return self.fn(x), state


@serializable
@dataclasses.dataclass
class PermuteLayer(Layer):
    """Permute non-batch axes (Keras Permute; 1-indexed dims like
    Keras). reference kin: KerasPermute mapper."""

    dims: Tuple[int, ...] = ()

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        dims = tuple(int(d) for d in self.dims)
        if it.kind == "recurrent" and dims == (2, 1):
            # [N,T,F] -> [N,F,T]
            return InputType.recurrent(it.timeseries_length or 0, it.size)
        if it.kind == "convolutional" and len(dims) == 3:
            hwc = (it.height, it.width, it.channels)
            p = tuple(hwc[d - 1] for d in dims)
            return InputType.convolutional(p[0], p[1], p[2])
        if dims == tuple(range(1, len(dims) + 1)):
            return it  # identity permutation
        raise ValueError(
            f"Permute{dims} unsupported for input kind {it.kind!r}")

    def apply(self, params, state, x, train, rng):
        perm = (0,) + tuple(int(d) for d in self.dims)
        return jnp.transpose(x, perm), state


@serializable
@dataclasses.dataclass
class ReshapeLayer(Layer):
    """Reshape non-batch axes (Keras Reshape). reference kin:
    KerasReshape mapper."""

    target_shape: Tuple[int, ...] = ()

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        ts = tuple(int(d) for d in self.target_shape)
        if len(ts) == 1:
            return InputType.feedForward(ts[0])
        if len(ts) == 2:
            return InputType.recurrent(ts[1], ts[0])
        if len(ts) == 3:
            return InputType.convolutional(ts[0], ts[1], ts[2])
        raise ValueError(f"unsupported Reshape target {ts}")

    def apply(self, params, state, x, train, rng):
        ts = tuple(int(d) for d in self.target_shape)
        return jnp.reshape(x, (x.shape[0],) + ts), state
