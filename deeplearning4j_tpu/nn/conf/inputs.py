"""Input types (reference: org/deeplearning4j/nn/conf/inputs/InputType
and InputPreProcessor machinery).

The reference's `setInputType` walks the layer list, infers each layer's
nIn, and inserts preprocessors (e.g. CnnToFeedForwardPreProcessor) at
representation changes. We keep the same mechanism but the canonical
image layout is **NHWC** (TPU/XLA-preferred; reference uses NCHW) —
`convolutionalFlat` reshapes flat vectors to NHWC.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.common.serde import serializable


@serializable
@dataclasses.dataclass
class InputType:
    """Tagged union: kind in {feedforward, recurrent, convolutional,
    convolutionalFlat}. Shapes exclude the batch dimension."""

    kind: str = "feedforward"
    size: int = 0           # feedforward width / recurrent feature size
    height: int = 0
    width: int = 0
    channels: int = 0
    depth: int = 0          # 3D convolutional only
    timeseries_length: int = -1  # -1 = variable

    # -- constructors mirroring the reference's static methods ---------
    @staticmethod
    def feedForward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=size)

    @staticmethod
    def recurrent(size: int, timeseries_length: int = -1) -> "InputType":
        return InputType(kind="recurrent", size=size,
                         timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=height, width=width,
                         channels=channels)

    @staticmethod
    def convolutional3D(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        """NDHWC volumetric input (reference: InputType.convolutional3D)."""
        return InputType(kind="convolutional3d", depth=depth, height=height,
                         width=width, channels=channels)

    @staticmethod
    def convolutionalFlat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutionalFlat", height=height, width=width,
                         channels=channels)

    # -- geometry -------------------------------------------------------
    def arrayElementsPerExample(self) -> int:
        if self.kind == "feedforward":
            return self.size
        if self.kind == "recurrent":
            return self.size * max(self.timeseries_length, 1)
        if self.kind == "convolutional3d":
            return self.depth * self.height * self.width * self.channels
        return self.height * self.width * self.channels

    def example_shape(self) -> Tuple[int, ...]:
        """Per-example array shape in canonical layout (NHWC images)."""
        if self.kind == "feedforward":
            return (self.size,)
        if self.kind == "recurrent":
            return (max(self.timeseries_length, 1), self.size)
        if self.kind == "convolutional":
            return (self.height, self.width, self.channels)
        if self.kind == "convolutional3d":
            return (self.depth, self.height, self.width, self.channels)
        if self.kind == "convolutionalFlat":
            return (self.height * self.width * self.channels,)
        raise ValueError(self.kind)

    def flat_size(self) -> int:
        return self.arrayElementsPerExample()
