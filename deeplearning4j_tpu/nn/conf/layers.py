"""Layer configurations + functional implementations.

Reference: org/deeplearning4j/nn/conf/layers/** (configs) and
org/deeplearning4j/nn/layers/** (impls) — SURVEY.md §2.18/§2.20. The
reference splits config (Jackson beans) from impl (stateful Layer
objects holding INDArray params); the TPU-native design fuses them: a
layer IS a serializable dataclass with pure functions

    init_params(key, input_type, dtype)      -> param dict
    init_state(input_type, dtype)            -> non-trainable state dict
    apply(params, state, x, train, rng)      -> (out, new_state)

so the whole network forward is a pure function jit-compiled as ONE XLA
program per step (replacing the reference's per-layer, per-op JNI hot
loop — SURVEY.md §3.1). Canonical layouts: images NHWC, sequences NTF.

Param names follow the reference (W, b, gamma/beta/mean/var for BN,
RW for recurrent weights) so checkpoints read naturally.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.common.serde import serializable, _tuplify
from deeplearning4j_tpu.loss import LossFunction, compute_loss
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights
from deeplearning4j_tpu.ops import nn as nnops


class PoolingType(enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


def _act(a) -> Activation:
    return Activation.resolve(a)


@dataclasses.dataclass
class Layer:
    """Base layer config. Fields set to None inherit network defaults
    (reference: NeuralNetConfiguration 'global config' cloning)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    updater: Optional[Any] = None        # per-layer updater override
    l1: Optional[float] = None
    l2: Optional[float] = None
    #: input dropout: float (classic) or an IDropout config
    #: (Alpha/Gaussian/Spatial — reference conf/dropout/**)
    dropout: Optional[Any] = None
    #: IWeightNoise (DropConnect/WeightNoise — reference weightnoise/**)
    weight_noise: Optional[Any] = None
    #: list of LayerConstraint applied post-update (reference constraint/**)
    constraints: Optional[Any] = None

    # -- to be overridden ----------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init_params(self, key, input_type: InputType, dtype) -> dict:
        return {}

    def init_state(self, input_type: InputType, dtype) -> dict:
        return {}

    def apply(self, params, state, x, train: bool, rng):
        raise NotImplementedError

    # -- recurrent-state API (reference: BaseRecurrentLayer#rnnTimeStep /
    # rnnActivateUsingStoredState; non-recurrent layers are stateless) ---
    is_recurrent = False  # class attr, not a field (keeps JSON serde clean)

    def init_carry(self, batch: int, dtype):
        """Initial hidden carry for stateful stepping / tBPTT."""
        return None

    def apply_with_carry(self, params, state, carry, x, train, rng):
        """Like apply(), but threads the recurrent hidden state.
        Returns (out, new_state, new_carry)."""
        out, ns = self.apply(params, state, x, train, rng)
        return out, ns, carry

    # -- shared helpers -------------------------------------------------
    def _maybe_dropout(self, x, train, rng):
        if train and self.dropout and rng is not None:
            if isinstance(self.dropout, (int, float)):
                return nnops.dropout(x, self.dropout, rng)
            return self.dropout.apply(x, rng)
        return x

    def has_params(self) -> bool:
        return True


# ----------------------------------------------------------------------
# feed-forward layers
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class DenseLayer(Layer):
    """Fully connected (reference: conf/layers/DenseLayer + impl
    BaseLayer#preOutput: z = xW + b). Applies over the last axis, so it
    is time-distributed over [N,T,F] input automatically."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def output_type(self, it: InputType) -> InputType:
        if it.kind == "recurrent":
            return InputType.recurrent(self.n_out, it.timeseries_length)
        return InputType.feedForward(self.n_out)

    def init_params(self, key, it: InputType, dtype) -> dict:
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return _act(self.activation or "identity").fn(z), state


@serializable
@dataclasses.dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference: conf/layers/OutputLayer).

    `loss_value` computes the masked mean loss from PRE-activations so
    the fused softmax+CE path is used (numerically stable on TPU)."""

    loss: str = "mcxent"

    def loss_value(self, params, state, x, labels, mask=None):
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return compute_loss(LossFunction.resolve(self.loss), labels, z,
                            self.activation or "softmax", mask)

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return _act(self.activation or "softmax").fn(z), state


@serializable
@dataclasses.dataclass
class LossLayer(Layer):
    """Parameterless loss head (reference: conf/layers/LossLayer)."""

    loss: str = "mse"

    def has_params(self):
        return False

    def loss_value(self, params, state, x, labels, mask=None):
        return compute_loss(LossFunction.resolve(self.loss), labels, x,
                            self.activation or "identity", mask)

    def apply(self, params, state, x, train, rng):
        return _act(self.activation or "identity").fn(x), state


@serializable
@dataclasses.dataclass
class CnnLossLayer(LossLayer):
    """Per-pixel loss head on [N,H,W,C] activations (reference:
    conf/layers/CnnLossLayer — segmentation heads like UNet). The loss
    math is elementwise, so LossLayer's fused paths apply unchanged."""


@serializable
@dataclasses.dataclass
class RnnLossLayer(LossLayer):
    """Per-timestep loss head on [N,T,C] activations (reference:
    conf/layers/RnnLossLayer)."""


@serializable
@dataclasses.dataclass
class ActivationLayer(Layer):
    #: parameter for parameterized activations (leakyrelu slope, elu α)
    alpha: Optional[float] = None

    def has_params(self):
        return False

    def apply(self, params, state, x, train, rng):
        a = _act(self.activation or "identity")
        if self.alpha is not None and a in (Activation.LEAKYRELU,
                                            Activation.ELU,
                                            Activation.THRESHOLDEDRELU):
            from deeplearning4j_tpu.ops.registry import get_op
            return get_op(a.value)(x, self.alpha), state
        return a.fn(x), state


@serializable
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (reference: conf/layers/DropoutLayer).
    ``rate`` is a float or any IDropout config (Alpha/Gaussian/Spatial)."""

    rate: Any = 0.5

    def has_params(self):
        return False

    def apply(self, params, state, x, train, rng):
        if train and rng is not None:
            if isinstance(self.rate, (int, float)):
                return nnops.dropout(x, self.rate, rng), state
            return self.rate.apply(x, rng), state
        return x, state


@serializable
@dataclasses.dataclass
class EmbeddingLayer(Layer):
    """Index -> vector lookup (reference: EmbeddingLayer /
    EmbeddingSequenceLayer; one-hot matmul in the reference, gather here).
    Accepts [N] or [N,T] int input; emits [N,n_out] or [N,T,n_out]."""

    n_in: int = 0     # vocab size
    n_out: int = 0

    def output_type(self, it: InputType) -> InputType:
        if it.kind == "recurrent":
            return InputType.recurrent(self.n_out, it.timeseries_length)
        return InputType.feedForward(self.n_out)

    def init_params(self, key, it, dtype) -> dict:
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        return {"W": w}

    def apply(self, params, state, x, train, rng):
        ids = x.astype(jnp.int32)
        if ids.ndim > 1 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        out = jnp.take(params["W"], ids, axis=0)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class EmbeddingSequenceLayer(EmbeddingLayer):
    """Sequence lookup: [N,T] int ids -> [N,T,n_out] recurrent
    (reference: conf/layers/EmbeddingSequenceLayer — the Keras
    Embedding analog)."""

    input_length: int = 0

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length if it.kind == "recurrent" else (
            self.input_length or it.size or -1)
        return InputType.recurrent(self.n_out, t)

    def apply(self, params, state, x, train, rng):
        ids = x.astype(jnp.int32)
        if ids.ndim == 1:  # [N] -> length-1 sequence
            ids = ids[:, None]
        # NO trailing-dim collapse here: [N,1] means seq length 1 and
        # must emit [N,1,n_out] (contrast EmbeddingLayer)
        out = jnp.take(params["W"], ids, axis=0)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class FlattenLayer(Layer):
    """Reshape any input to [N, flat] (reference analog: the
    CnnToFeedForward / RnnToFeedForward preprocessors as an explicit
    layer; used by Keras-import Flatten)."""

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        if it.kind == "recurrent" and it.timeseries_length in (-1, None):
            raise ValueError(
                "FlattenLayer needs a fixed timeseries length")
        return InputType.feedForward(it.flat_size())

    def apply(self, params, state, x, train, rng):
        return x.reshape(x.shape[0], -1), state


@serializable
@dataclasses.dataclass
class LastTimeStep(Layer):
    """Wrap a recurrent layer, emit only its final time step
    (reference: conf/layers/recurrent/LastTimeStep — the Keras
    return_sequences=False analog)."""

    underlying: Optional[Layer] = None

    def has_params(self):
        return self.underlying.has_params()

    def output_type(self, it: InputType) -> InputType:
        ot = self.underlying.output_type(it)
        return InputType.feedForward(ot.size)

    def init_params(self, key, it, dtype) -> dict:
        return self.underlying.init_params(key, it, dtype)

    def init_state(self, it, dtype) -> dict:
        return self.underlying.init_state(it, dtype)

    def apply(self, params, state, x, train, rng):
        out, st = self.underlying.apply(params, state, x, train, rng)
        return out[:, -1, :], st

    @property
    def is_recurrent(self):
        return self.underlying is not None and self.underlying.is_recurrent

    def init_carry(self, batch, dtype):
        return self.underlying.init_carry(batch, dtype)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        out, st, c = self.underlying.apply_with_carry(
            params, state, carry, x, train, rng)
        return out[:, -1, :], st, c


# ----------------------------------------------------------------------
# convolutional layers
# ----------------------------------------------------------------------
def _conv_out(size, k, s, mode, pad, dilation=1):
    if mode == "Same":
        return -(-size // s)
    k_eff = (k - 1) * dilation + 1  # dilated (atrous) effective kernel
    return (size - k_eff + 2 * pad) // s + 1


@serializable
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2D conv (reference: conf/layers/ConvolutionLayer; impl dispatches
    to CudnnConvolutionHelper — here XLA's MXU conv IS the fast path).

    convolution_mode: 'Same' | 'Truncate' (reference ConvolutionMode;
    Truncate = VALID with explicit padding)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "Truncate"
    dilation: Tuple[int, int] = (1, 1)
    has_bias: bool = True

    def __post_init__(self):
        self.kernel_size = _tuplify(self.kernel_size)
        self.stride = _tuplify(self.stride)
        self.padding = _tuplify(self.padding)
        self.dilation = _tuplify(self.dilation)

    def output_type(self, it: InputType) -> InputType:
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0],
                      self.convolution_mode, self.padding[0], self.dilation[0])
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1],
                      self.convolution_mode, self.padding[1], self.dilation[1])
        return InputType.convolutional(h, w, self.n_out)

    def _pad_arg(self):
        if self.convolution_mode == "Same":
            return "SAME"
        return self.padding

    def init_params(self, key, it, dtype) -> dict:
        kh, kw = self.kernel_size
        fan_in = kh * kw * self.n_in
        fan_out = kh * kw * self.n_out
        w = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (kh, kw, self.n_in, self.n_out), fan_in, fan_out, dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        out = nnops.conv2d(x, params["W"], params.get("b"),
                           strides=self.stride, padding=self._pad_arg(),
                           dilation=self.dilation)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class SeparableConvolution2D(ConvolutionLayer):
    depth_multiplier: int = 1

    def init_params(self, key, it, dtype) -> dict:
        kh, kw = self.kernel_size
        k1, k2 = jax.random.split(key)
        dw = init_weights(self.weight_init or WeightInit.XAVIER, k1,
                          (kh, kw, self.n_in, self.depth_multiplier),
                          kh * kw * self.n_in, kh * kw * self.n_in, dtype)
        pw = init_weights(self.weight_init or WeightInit.XAVIER, k2,
                          (1, 1, self.n_in * self.depth_multiplier, self.n_out),
                          self.n_in * self.depth_multiplier, self.n_out, dtype)
        p = {"dW": dw, "pW": pw}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        out = nnops.separable_conv2d(x, params["dW"], params["pW"],
                                     params.get("b"), strides=self.stride,
                                     padding=self._pad_arg() if self.convolution_mode == "Same" else self.padding,
                                     dilation=self.dilation)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling (reference: conf/layers/SubsamplingLayer)."""

    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "Truncate"
    pnorm: int = 2

    def __post_init__(self):
        self.kernel_size = _tuplify(self.kernel_size)
        self.stride = _tuplify(self.stride)
        self.padding = _tuplify(self.padding)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0],
                      self.convolution_mode, self.padding[0])
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1],
                      self.convolution_mode, self.padding[1])
        return InputType.convolutional(h, w, it.channels)

    def apply(self, params, state, x, train, rng):
        pad = "SAME" if self.convolution_mode == "Same" else (
            "VALID" if self.padding == (0, 0) else self.padding)
        pt = PoolingType(self.pooling_type)
        if pt is PoolingType.MAX:
            return nnops.maxpool2d(x, self.kernel_size, self.stride, pad), state
        if pt is PoolingType.AVG:
            return nnops.avgpool2d(x, self.kernel_size, self.stride, pad), state
        if pt is PoolingType.PNORM:
            return nnops.pnormpool2d(x, self.kernel_size, self.stride, pad, self.pnorm), state
        return nnops.sumpool2d(x, self.kernel_size, self.stride, pad), state


@serializable
@dataclasses.dataclass
class Upsampling2D(Layer):
    size: int = 2

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return InputType.convolutional(it.height * self.size,
                                       it.width * self.size, it.channels)

    def apply(self, params, state, x, train, rng):
        return nnops.upsampling2d(x, self.size), state


@serializable
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    pad: Tuple[int, int] = (1, 1)

    def __post_init__(self):
        self.pad = _tuplify(self.pad)

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        return InputType.convolutional(it.height + 2 * self.pad[0],
                                       it.width + 2 * self.pad[1], it.channels)

    def apply(self, params, state, x, train, rng):
        p = self.pad
        return jnp.pad(x, ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))), state


@serializable
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or time dims (reference:
    conf/layers/GlobalPoolingLayer; collapses CNN/RNN to FF)."""

    pooling_type: str = "max"

    def has_params(self):
        return False

    def output_type(self, it: InputType) -> InputType:
        if it.kind == "recurrent":
            return InputType.feedForward(it.size)
        return InputType.feedForward(it.channels)

    def apply(self, params, state, x, train, rng):
        axes = tuple(range(1, x.ndim - 1))
        pt = PoolingType(self.pooling_type)
        if pt is PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if pt is PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        if pt is PoolingType.PNORM:
            return jnp.sum(jnp.abs(x) ** 2, axis=axes) ** 0.5, state
        return jnp.mean(x, axis=axes), state

    def apply_masked(self, params, state, x, mask, train, rng):
        """Pool over REAL timesteps only (reference: GlobalPoolingLayer
        masked pooling via setMaskArray). x: [N,T,F]; mask: [N,T]."""
        m = mask[..., None].astype(x.dtype)
        pt = PoolingType(self.pooling_type)
        if pt is PoolingType.MAX:
            neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
            return jnp.max(jnp.where(m > 0, x, neg), axis=1), state
        if pt is PoolingType.SUM:
            return jnp.sum(x * m, axis=1), state
        if pt is PoolingType.PNORM:
            return jnp.sum(jnp.abs(x * m) ** 2, axis=1) ** 0.5, state
        return jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1),
                                                    1.0), state


# ----------------------------------------------------------------------
# normalization layers
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class BatchNormalization(Layer):
    """Batch norm (reference: conf/layers/BatchNormalization + cuDNN
    helper). Running stats live in layer STATE (functional update each
    train step), matching the reference's global-mean/var arrays.

    decay follows the reference: running = decay*running + (1-decay)*batch.
    """

    eps: float = 1e-5
    decay: float = 0.9
    use_log_std: bool = False  # parity knob with reference's config

    def _nf(self, it: InputType) -> int:
        return it.channels if it.kind == "convolutional" else it.size

    def init_params(self, key, it, dtype) -> dict:
        n = self._nf(it)
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}

    def init_state(self, it, dtype) -> dict:
        n = self._nf(it)
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    def apply(self, params, state, x, train, rng):
        if train:
            act = self.activation or "identity"
            if act == "relu" and nnops.FUSED_BN_RELU_BWD:
                # fused forward + hand two-pass backward (relu mask
                # recomputed in-fusion; see batch_norm_relu_train)
                y, m, v = nnops.batch_norm_relu_train(
                    x, params["gamma"], params["beta"], self.eps)
                d = self.decay
                return y, {"mean": d * state["mean"] + (1 - d) * m,
                           "var": d * state["var"] + (1 - d) * v}
            y, m, v = nnops.batch_norm_train(x, params["gamma"], params["beta"],
                                             self.eps)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * m,
                         "var": d * state["var"] + (1 - d) * v}
            out = y
        else:
            out = nnops.batch_norm(x, params["gamma"], params["beta"],
                                   state["mean"], state["var"], self.eps)
            new_state = state
        return _act(self.activation or "identity").fn(out), new_state


@serializable
@dataclasses.dataclass
class LayerNormalization(Layer):
    """Layer norm over the feature axis (transformer building block)."""

    eps: float = 1e-5

    def _nf(self, it: InputType) -> int:
        return it.channels if it.kind == "convolutional" else it.size

    def init_params(self, key, it, dtype) -> dict:
        n = self._nf(it)
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}

    def apply(self, params, state, x, train, rng):
        return nnops.layer_norm(x, params["gamma"], params["beta"],
                                eps=self.eps), state


@serializable
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """LRN (reference: conf/layers/LocalResponseNormalization)."""

    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self):
        return False

    def apply(self, params, state, x, train, rng):
        return nnops.local_response_normalization(
            x, depth_radius=self.n // 2, bias=self.k, alpha=self.alpha,
            beta=self.beta), state


# ----------------------------------------------------------------------
# recurrent layers
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class LSTM(Layer):
    """LSTM (reference: conf/layers/LSTM; impl layers/recurrent/LSTM with
    CudnnLSTMHelper fast path). Single fused lax.scan, gate order IFGO.
    Weight names follow the reference: W (input), RW (recurrent), b.

    forget_gate_bias_init: the reference initializes the forget-gate bias
    (commonly 1.0) to stabilize early training.
    """

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        k1, k2 = jax.random.split(key)
        h = self.n_out
        w = init_weights(self.weight_init or WeightInit.XAVIER, k1,
                         (self.n_in, 4 * h), self.n_in, 4 * h, dtype)
        rw = init_weights(self.weight_init or WeightInit.XAVIER, k2,
                          (h, 4 * h), h, 4 * h, dtype)
        b = jnp.zeros((4 * h,), dtype)
        # gate order i,f,g,o — bias the forget gate
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        return {"W": w, "RW": rw, "b": b}

    is_recurrent = True

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        ys, _ = nnops.lstm_layer(x, params["W"], params["RW"], params["b"])
        act = self.activation
        return (_act(act).fn(ys) if act and act != "tanh" else ys), state

    def init_carry(self, batch, dtype):
        h = self.n_out
        return (jnp.zeros((batch, h), dtype), jnp.zeros((batch, h), dtype))

    def apply_with_carry(self, params, state, carry, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        ys, new_carry = nnops.lstm_layer(
            x, params["W"], params["RW"], params["b"],
            h0=carry[0], c0=carry[1])
        act = self.activation
        ys = _act(act).fn(ys) if act and act != "tanh" else ys
        return ys, state, new_carry


@serializable
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """Alias of LSTM (reference's GravesLSTM adds peephole connections;
    the fused TPU path omits peepholes — documented deviation, the
    reference itself deprecated GravesLSTM in favor of LSTM)."""


@serializable
@dataclasses.dataclass
class SimpleRnn(Layer):
    n_in: int = 0
    n_out: int = 0

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        k1, k2 = jax.random.split(key)
        w = init_weights(self.weight_init or WeightInit.XAVIER, k1,
                         (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        rw = init_weights(self.weight_init or WeightInit.XAVIER, k2,
                          (self.n_out, self.n_out), self.n_out, self.n_out, dtype)
        return {"W": w, "RW": rw, "b": jnp.zeros((self.n_out,), dtype)}

    is_recurrent = True

    def apply(self, params, state, x, train, rng):
        ys, _ = nnops.simple_rnn_layer(x, params["W"], params["RW"], params["b"])
        return ys, state

    def init_carry(self, batch, dtype):
        return jnp.zeros((batch, self.n_out), dtype)

    def apply_with_carry(self, params, state, carry, x, train, rng):
        ys, new_carry = nnops.simple_rnn_layer(
            x, params["W"], params["RW"], params["b"], h0=carry)
        return ys, state, new_carry


@serializable
@dataclasses.dataclass
class Bidirectional(Layer):
    """Bidirectional RNN wrapper (reference:
    conf/layers/recurrent/Bidirectional.java — wraps any recurrent layer
    with independent forward/backward copies, merged by Mode).

    TPU design: both directions are independent lax.scans over the same
    time-batched input projection; XLA schedules them concurrently. The
    backward direction runs the wrapped layer on the time-reversed input
    and un-reverses the output, so ANY recurrent layer conf works
    unmodified. Stateful stepping (rnnTimeStep) is unsupported, matching
    the reference (bidirectional needs the full sequence).
    """

    layer: Optional[Layer] = None
    mode: str = "CONCAT"  # CONCAT | ADD | MUL | AVERAGE
    #: False = Keras Bidirectional(return_sequences=False) semantics:
    #: merge(fwd LAST step, bwd last step — i.e. its output at input
    #: t=0), emitting [N, out] instead of a sequence
    return_sequences: bool = True

    is_recurrent = True

    @property
    def n_out(self):
        n = self.layer.n_out
        return 2 * n if self.mode.upper() == "CONCAT" else n

    @property
    def n_in(self):
        return self.layer.n_in

    @n_in.setter
    def n_in(self, v):
        self.layer.n_in = v

    def output_type(self, it: InputType) -> InputType:
        if not self.return_sequences:
            return InputType.feedForward(self.n_out)
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        k1, k2 = jax.random.split(key)
        return {"fw": self.layer.init_params(k1, it, dtype),
                "bw": self.layer.init_params(k2, it, dtype)}

    def init_state(self, it, dtype) -> dict:
        return {}

    def apply(self, params, state, x, train, rng):
        yf, _ = self.layer.apply(params["fw"], {}, x, train, rng)
        yb, _ = self.layer.apply(params["bw"], {}, jnp.flip(x, axis=1),
                                 train, rng)
        yb = jnp.flip(yb, axis=1)
        if not self.return_sequences:
            # Keras last-step rule: fwd's final output + bwd's final
            # output (the bwd scan ends at input t=0, where the
            # un-flipped sequence holds it)
            yf, yb = yf[:, -1], yb[:, 0]
        m = self.mode.upper()
        if m == "CONCAT":
            return jnp.concatenate([yf, yb], axis=-1), state
        if m == "ADD":
            return yf + yb, state
        if m == "MUL":
            return yf * yb, state
        if m == "AVERAGE":
            return 0.5 * (yf + yb), state
        raise ValueError(f"Unknown Bidirectional mode: {self.mode}")

    def init_carry(self, batch, dtype):
        raise NotImplementedError(
            "rnnTimeStep is not supported for Bidirectional layers "
            "(reference behavior: requires the full sequence)")


@serializable
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep output head (reference: conf/layers/RnnOutputLayer).
    DenseLayer applies over the last axis so the same math works on
    [N,T,F]; loss averages over time (mask-aware)."""

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)


# ----------------------------------------------------------------------
# attention
# ----------------------------------------------------------------------
@serializable
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Multi-head self-attention (reference: conf/layers/SelfAttentionLayer
    backed by the multiHeadDotProductAttention op)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0

    def __post_init__(self):
        if not self.head_size and self.n_heads:
            self.head_size = (self.n_out or self.n_in) // self.n_heads

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        proj = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            "Wq": init_weights(wi, ks[0], (self.n_in, proj), self.n_in, proj, dtype),
            "Wk": init_weights(wi, ks[1], (self.n_in, proj), self.n_in, proj, dtype),
            "Wv": init_weights(wi, ks[2], (self.n_in, proj), self.n_in, proj, dtype),
            "Wo": init_weights(wi, ks[3], (proj, self.n_out), proj, self.n_out, dtype),
        }

    def apply(self, params, state, x, train, rng):
        out = nnops.multi_head_dot_product_attention(
            x, x, params["Wq"], params["Wk"], params["Wv"], params["Wo"],
            num_heads=self.n_heads)
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class LearnedSelfAttentionLayer(Layer):
    """Attention with nQueries LEARNED query vectors (reference:
    conf/layers/LearnedSelfAttentionLayer — pools a variable-length
    sequence into a fixed number of query slots). Output is recurrent
    with timeseries length == n_queries.
    """

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0
    n_queries: int = 1

    def __post_init__(self):
        if not self.head_size and self.n_heads:
            self.head_size = (self.n_out or self.n_in) // self.n_heads

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, self.n_queries)

    def init_params(self, key, it, dtype) -> dict:
        proj = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            # learned queries, already in projection space
            "Q": init_weights(wi, ks[0], (self.n_queries, proj),
                              self.n_queries, proj, dtype),
            "Wk": init_weights(wi, ks[1], (self.n_in, proj), self.n_in, proj, dtype),
            "Wv": init_weights(wi, ks[2], (self.n_in, proj), self.n_in, proj, dtype),
            "Wo": init_weights(wi, ks[3], (proj, self.n_out), proj, self.n_out, dtype),
        }

    def apply(self, params, state, x, train, rng):
        n = x.shape[0]
        h, dh = self.n_heads, self.head_size
        q = jnp.broadcast_to(params["Q"], (n,) + params["Q"].shape)
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        split = lambda a: a.reshape(a.shape[0], a.shape[1], h, dh).transpose(0, 2, 1, 3)
        out = nnops.dot_product_attention(split(q), split(k), split(v))
        out = out.transpose(0, 2, 1, 3).reshape(n, self.n_queries, h * dh)
        out = out @ params["Wo"]
        return _act(self.activation or "identity").fn(out), state


@serializable
@dataclasses.dataclass
class RecurrentAttentionLayer(Layer):
    """Recurrent cell attending over the full input sequence each step
    (reference: conf/layers/RecurrentAttentionLayer — h_t depends on
    x_t, h_{t-1}, and attention(query=h_{t-1}, keys/values=X)).

    TPU design: K/V projections of the whole sequence are computed once
    as big MXU matmuls outside the scan; the scan carries h and does the
    O(T) attention read per step (O(T^2) total, like the reference).
    """

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0

    is_recurrent = True

    def __post_init__(self):
        if not self.head_size and self.n_heads:
            self.head_size = (self.n_out or self.n_in) // self.n_heads

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init_params(self, key, it, dtype) -> dict:
        proj = self.n_heads * self.head_size
        ks = jax.random.split(key, 6)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            "W": init_weights(wi, ks[0], (self.n_in, self.n_out), self.n_in, self.n_out, dtype),
            "RW": init_weights(wi, ks[1], (self.n_out, self.n_out), self.n_out, self.n_out, dtype),
            "Wq": init_weights(wi, ks[2], (self.n_out, proj), self.n_out, proj, dtype),
            "Wk": init_weights(wi, ks[3], (self.n_in, proj), self.n_in, proj, dtype),
            "Wv": init_weights(wi, ks[4], (self.n_in, proj), self.n_in, proj, dtype),
            "Wa": init_weights(wi, ks[5], (proj, self.n_out), proj, self.n_out, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, state, x, train, rng):
        h0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)
        ys, _ = self._scan(params, h0, x)
        return ys, state

    def _scan(self, params, h0, x):
        n, t, _ = x.shape
        heads, dh = self.n_heads, self.head_size
        # precompute K/V once: [N, heads, T, dh]
        k = (x.reshape(n * t, -1) @ params["Wk"]).reshape(n, t, heads, dh).transpose(0, 2, 1, 3)
        v = (x.reshape(n * t, -1) @ params["Wv"]).reshape(n, t, heads, dh).transpose(0, 2, 1, 3)
        x_proj = (x.reshape(n * t, -1) @ params["W"] + params["b"]) \
            .reshape(n, t, self.n_out).transpose(1, 0, 2)
        act = _act(self.activation or "tanh").fn

        def step(h, xp):
            q = (h @ params["Wq"]).reshape(n, heads, 1, dh)
            a = nnops.dot_product_attention(q, k, v)           # [N,heads,1,dh]
            a = a.reshape(n, heads * dh) @ params["Wa"]
            h2 = act(xp + h @ params["RW"] + a)
            return h2, h2

        hT, ys = jax.lax.scan(step, h0, x_proj)
        return ys.transpose(1, 0, 2), hT

    def init_carry(self, batch, dtype):
        # stateful stepping is full-sequence-dependent; reference treats
        # this layer as requiring complete sequences too
        raise NotImplementedError(
            "rnnTimeStep is not supported for RecurrentAttentionLayer "
            "(attends over the full input sequence)")
