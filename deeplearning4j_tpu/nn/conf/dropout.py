"""Dropout family (reference: org/deeplearning4j/nn/conf/dropout/** —
IDropout implementations: Dropout, AlphaDropout, GaussianDropout,
GaussianNoise, SpatialDropout; SURVEY.md §2.18/§2.20).

Each is a serializable config whose ``apply(x, rng)`` runs only in
training mode; layers accept either a plain float (classic inverted
dropout, backward compatible) or one of these objects in their
``dropout`` field. All noise is generated on device from the step's
fold-in key, so the whole train step stays one XLA executable.

Note on semantics: ``rate`` here is the DROP probability (matching this
framework's ops); the reference's ``Dropout(x)`` constructor takes the
RETAIN probability — the builders' dropOut() converts.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable


class IDropout:
    """Marker base (reference: IDropout interface)."""

    def apply(self, x, rng):  # pragma: no cover - interface
        raise NotImplementedError


@serializable
@dataclasses.dataclass
class Dropout(IDropout):
    """Inverted dropout (reference: conf/dropout/Dropout)."""

    rate: float = 0.5

    def apply(self, x, rng):
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@serializable
@dataclasses.dataclass
class SpatialDropout(IDropout):
    """Drops whole feature maps/channels (reference:
    conf/dropout/SpatialDropout). For [N,H,W,C] or [N,T,F] input the
    mask is drawn per (batch, channel) and broadcast over the spatial/
    time axes — decorrelated activations drop together."""

    rate: float = 0.5

    def apply(self, x, rng):
        keep = 1.0 - self.rate
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@serializable
@dataclasses.dataclass
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise N(1, rate/(1-rate)) (reference:
    conf/dropout/GaussianDropout — Srivastava et al.'s gaussian
    variant; mean-preserving, so no inference-time rescale)."""

    rate: float = 0.5

    def apply(self, x, rng):
        std = jnp.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + std * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise


@serializable
@dataclasses.dataclass
class GaussianNoise(IDropout):
    """Additive zero-mean gaussian noise (reference:
    conf/dropout/GaussianNoise)."""

    stddev: float = 0.1

    def apply(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


# SELU fixed-point constants (Klambauer et al. 2017)
_ALPHA = 1.6732632423543772
_SCALE = 1.0507009873554805
_ALPHA_PRIME = -_SCALE * _ALPHA


@serializable
@dataclasses.dataclass
class AlphaDropout(IDropout):
    """Self-normalizing dropout for SELU nets (reference:
    conf/dropout/AlphaDropout). Dropped units are set to alpha' and the
    output is affine-corrected so mean/variance are preserved."""

    rate: float = 0.5

    def apply(self, x, rng):
        keep = 1.0 - self.rate
        a = (keep + _ALPHA_PRIME ** 2 * keep * (1.0 - keep)) ** -0.5
        b = -a * _ALPHA_PRIME * (1.0 - keep)
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return (a * jnp.where(mask, x, _ALPHA_PRIME) + b).astype(x.dtype)


def resolve_dropout(d):
    """float -> Dropout(rate); IDropout -> itself; None -> None."""
    if d is None:
        return None
    if isinstance(d, IDropout):
        return d
    return Dropout(rate=float(d))
