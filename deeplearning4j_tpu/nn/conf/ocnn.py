"""One-class neural network output layer (reference:
org/deeplearning4j/nn/conf/ocnn/OCNNOutputLayer + impl
org/deeplearning4j/nn/layers/ocnn/OCNNOutputLayer — anomaly detection
head per Chalapathy et al., "Anomaly Detection using One-Class Neural
Networks": min_{V,w,r} 0.5||V||^2 + 0.5||w||^2
+ (1/nu) * mean(relu(r - w . g(xV))) - r, trained on 'normal' data
only; labels are ignored).

TPU-native design note on r: the reference recomputes r every
``windowSize`` iterations as the nu-quantile of the last window's
scores (a host-side sort). Here r is a TRAINABLE scalar updated by the
same compiled step as V and w: dLoss/dr = mean(1[score < r])/nu - 1,
so gradient descent drives mean(1[score < r]) -> nu, i.e. r converges
to the same nu-quantile fixed point with no host round-trip or
windowed sort. ``initial_r_value`` mirrors the reference knob.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, _act
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights


@serializable
@dataclasses.dataclass
class OCNNOutputLayer(Layer):
    """One-class output head. ``fit(x, y)``'s labels are IGNORED (pass
    zeros); the layer's activation (default relu) is the hidden g().
    Inference output is the decision value ``w . g(xV) - r`` per
    example ([N, 1]; >= 0 means 'normal')."""

    n_in: int = 0
    hidden_size: int = 64
    nu: float = 0.04
    initial_r_value: float = 0.1

    def output_type(self, it: InputType) -> InputType:
        return InputType.feedForward(1)

    def init_params(self, key, it: InputType, dtype) -> dict:
        v = init_weights(self.weight_init or WeightInit.XAVIER, key,
                         (self.n_in, self.hidden_size), self.n_in,
                         self.hidden_size, dtype)
        return {"V": v,
                "W": jnp.full((self.hidden_size,), 1.0 / self.hidden_size,
                              dtype),
                "r": jnp.asarray(self.initial_r_value, jnp.float32)}

    def _scores(self, params, x):
        g = _act(self.activation or "relu")
        h = g.fn(x @ params["V"])
        return (h @ params["W"]).astype(jnp.float32)

    def loss_value(self, params, state, x, labels, mask=None):
        # labels deliberately unused: one-class training
        s = self._scores(params, x)
        r = params["r"]
        hinge = jnp.maximum(0.0, r - s)
        if mask is not None:
            m = mask.astype(hinge.dtype).reshape(hinge.shape)
            hinge_mean = jnp.sum(hinge * m) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            hinge_mean = jnp.mean(hinge)
        vf = params["V"].astype(jnp.float32)
        wf = params["W"].astype(jnp.float32)
        return (0.5 * jnp.sum(vf * vf) + 0.5 * jnp.sum(wf * wf)
                + hinge_mean / self.nu - r)

    def apply(self, params, state, x, train, rng):
        x = self._maybe_dropout(x, train, rng)
        return (self._scores(params, x) - params["r"])[:, None], state
