"""Weight constraints (reference: org/deeplearning4j/nn/conf/constraint/
** — BaseConstraint subclasses MaxNormConstraint, MinMaxNormConstraint,
UnitNormConstraint, NonNegativeConstraint; SURVEY.md §2.18).

Applied AFTER the updater step, inside the compiled train step
(reference: BaseConstraint#applyConstraint called post-update), to the
layer's weight params. Configure via ``Layer.constraints`` (a list).

Norms are computed over the fan-in axes (all but the last — for a
[k..., in, out] weight each output unit's incoming vector), matching the
reference's dimension defaults.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.nn.conf.weightnoise import WEIGHT_KEYS


class LayerConstraint:
    """Marker base (reference: api/layers/LayerConstraint)."""

    def _constrain_one(self, w):  # pragma: no cover - interface
        raise NotImplementedError

    def apply(self, params: dict) -> dict:
        out = dict(params)
        for k in params:
            if k in WEIGHT_KEYS:
                out[k] = self._constrain_one(params[k])
        return out


def _unit_axes(w) -> Tuple[int, ...]:
    """Fan-in axes: everything except the output (last) axis."""
    return tuple(range(w.ndim - 1)) if w.ndim > 1 else (0,)


@serializable
@dataclasses.dataclass
class MaxNormConstraint(LayerConstraint):
    """Clip each output unit's incoming-weight L2 norm to max_norm
    (reference: constraint/MaxNormConstraint)."""

    max_norm: float = 2.0

    def _constrain_one(self, w):
        axes = _unit_axes(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True) + 1e-12)
        return w * jnp.minimum(1.0, self.max_norm / norm)


@serializable
@dataclasses.dataclass
class MinMaxNormConstraint(LayerConstraint):
    """Rescale unit norms into [min, max] with strength ``rate``
    (reference: constraint/MinMaxNormConstraint)."""

    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def _constrain_one(self, w):
        axes = _unit_axes(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True) + 1e-12)
        clipped = jnp.clip(norm, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * norm
        return w * (target / norm)


@serializable
@dataclasses.dataclass
class UnitNormConstraint(LayerConstraint):
    """Normalize each unit's incoming weights to L2 norm 1 (reference:
    constraint/UnitNormConstraint)."""

    def _constrain_one(self, w):
        axes = _unit_axes(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True) + 1e-12)
        return w / norm


@serializable
@dataclasses.dataclass
class NonNegativeConstraint(LayerConstraint):
    """Clamp weights at >= 0 (reference: constraint/NonNegativeConstraint)."""

    def _constrain_one(self, w):
        return jnp.maximum(w, 0.0)


def apply_constraints(layer, params: dict) -> dict:
    """Apply a layer's configured constraints post-update."""
    cs = getattr(layer, "constraints", None)
    if not cs:
        return params
    for c in cs:
        params = c.apply(params)
    return params
