"""Network configuration system (reference: org/deeplearning4j/nn/conf/**
— NeuralNetConfiguration, MultiLayerConfiguration, layer confs, input
types/preprocessors, with guaranteed JSON round-trip. SURVEY.md §2.18).
"""

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    DenseLayer,
    DropoutLayer, EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer, GravesLSTM, LSTM,
    Layer, LossLayer, OutputLayer, PoolingType, RnnOutputLayer,
    SubsamplingLayer, SeparableConvolution2D, Upsampling2D, ZeroPaddingLayer,
    LayerNormalization, SelfAttentionLayer, LocalResponseNormalization,
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, LastTimeStep, SimpleRnn,
    CnnLossLayer, RnnLossLayer,
)
from deeplearning4j_tpu.nn.conf.layers_extra import (
    CapsuleLayer, CapsuleStrengthLayer, CenterLossOutputLayer, Convolution1D,
    Convolution3D, Cropping1D, Cropping2D, Cropping3D, Deconvolution2D,
    Deconvolution3D,
    DepthwiseConvolution2D, ElementWiseMultiplicationLayer, GravesBidirectionalLSTM, GRU,
    LambdaLayer,
    LocallyConnected1D, LocallyConnected2D, MaskLayer, MaskZeroLayer,
    PReLULayer, PrimaryCapsules, RepeatVector, SpaceToBatchLayer,
    SpaceToDepthLayer, Subsampling1DLayer, Subsampling3DLayer, Upsampling1D,
    Upsampling3D, ZeroPadding1DLayer, ZeroPadding3DLayer,
)
from deeplearning4j_tpu.nn.conf.variational import (
    AutoEncoder, VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.conf.ocnn import OCNNOutputLayer
from deeplearning4j_tpu.nn.conf.dropout import (
    AlphaDropout, Dropout, GaussianDropout, GaussianNoise, IDropout,
    SpatialDropout,
)
from deeplearning4j_tpu.nn.conf.weightnoise import (
    DropConnect, IWeightNoise, WeightNoise,
)
from deeplearning4j_tpu.nn.conf.constraint import (
    LayerConstraint, MaxNormConstraint, MinMaxNormConstraint,
    NonNegativeConstraint, UnitNormConstraint,
)
from deeplearning4j_tpu.nn.conf.builder import (
    MultiLayerConfiguration, NeuralNetConfiguration,
)

__all__ = [
    "InputType", "Layer", "Bidirectional", "DenseLayer", "ConvolutionLayer",
    "SubsamplingLayer", "BatchNormalization", "OutputLayer", "LossLayer",
    "DropoutLayer", "ActivationLayer", "EmbeddingLayer",
    "EmbeddingSequenceLayer",
    "GlobalPoolingLayer", "LSTM", "GravesLSTM", "RnnOutputLayer",
    "PoolingType", "SeparableConvolution2D", "Upsampling2D",
    "ZeroPaddingLayer", "LayerNormalization", "SelfAttentionLayer",
    "LocalResponseNormalization", "LearnedSelfAttentionLayer",
    "RecurrentAttentionLayer", "LastTimeStep", "SimpleRnn",
    "CnnLossLayer", "RnnLossLayer",
    "CapsuleLayer", "CapsuleStrengthLayer", "CenterLossOutputLayer",
    "Convolution1D", "Convolution3D", "Cropping1D", "Cropping2D",
    "Cropping3D", "Deconvolution2D", "Deconvolution3D",
    "DepthwiseConvolution2D",
    "ElementWiseMultiplicationLayer", "GravesBidirectionalLSTM", "GRU",
    "LambdaLayer", "LocallyConnected1D",
    "LocallyConnected2D", "MaskLayer", "MaskZeroLayer", "PReLULayer",
    "PrimaryCapsules", "RepeatVector", "SpaceToBatchLayer",
    "SpaceToDepthLayer", "Subsampling1DLayer", "Subsampling3DLayer",
    "Upsampling1D", "Upsampling3D", "ZeroPadding1DLayer",
    "ZeroPadding3DLayer",
    "AlphaDropout", "Dropout", "GaussianDropout", "GaussianNoise",
    "IDropout", "SpatialDropout",
    "DropConnect", "IWeightNoise", "WeightNoise",
    "LayerConstraint", "MaxNormConstraint", "MinMaxNormConstraint",
    "NonNegativeConstraint", "UnitNormConstraint",
    "AutoEncoder", "VariationalAutoencoder", "OCNNOutputLayer",
    "MultiLayerConfiguration", "NeuralNetConfiguration",
]
