"""Network configuration system (reference: org/deeplearning4j/nn/conf/**
— NeuralNetConfiguration, MultiLayerConfiguration, layer confs, input
types/preprocessors, with guaranteed JSON round-trip. SURVEY.md §2.18).
"""

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    DenseLayer,
    DropoutLayer, EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer, GravesLSTM, LSTM,
    Layer, LossLayer, OutputLayer, PoolingType, RnnOutputLayer,
    SubsamplingLayer, SeparableConvolution2D, Upsampling2D, ZeroPaddingLayer,
    LayerNormalization, SelfAttentionLayer, LocalResponseNormalization,
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, LastTimeStep, SimpleRnn,
    CnnLossLayer, RnnLossLayer,
)
from deeplearning4j_tpu.nn.conf.builder import (
    MultiLayerConfiguration, NeuralNetConfiguration,
)

__all__ = [
    "InputType", "Layer", "Bidirectional", "DenseLayer", "ConvolutionLayer",
    "SubsamplingLayer", "BatchNormalization", "OutputLayer", "LossLayer",
    "DropoutLayer", "ActivationLayer", "EmbeddingLayer",
    "EmbeddingSequenceLayer",
    "GlobalPoolingLayer", "LSTM", "GravesLSTM", "RnnOutputLayer",
    "PoolingType", "SeparableConvolution2D", "Upsampling2D",
    "ZeroPaddingLayer", "LayerNormalization", "SelfAttentionLayer",
    "LocalResponseNormalization", "LearnedSelfAttentionLayer",
    "RecurrentAttentionLayer", "LastTimeStep", "SimpleRnn",
    "CnnLossLayer", "RnnLossLayer",
    "MultiLayerConfiguration", "NeuralNetConfiguration",
]
