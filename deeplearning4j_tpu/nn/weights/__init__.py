"""Weight initialization (reference: org/deeplearning4j/nn/weights/** —
WeightInit enum + IWeightInit impls, SURVEY.md §2.17).

Fan-in/fan-out semantics follow the reference's WeightInitUtil: for
dense [in, out] fanIn=in, fanOut=out; for convs fanIn=kh*kw*cin,
fanOut=kh*kw*cout. All draws take an explicit jax PRNG key (the trainer
splits keys deterministically at init, so init is reproducible from the
model seed — matching the reference's seeded RNG contract).
"""

from __future__ import annotations

import enum

import math

import jax
import jax.numpy as jnp


class WeightInit(enum.Enum):
    """Reference: org.deeplearning4j.nn.weights.WeightInit."""

    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    NORMAL = "normal"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    RELU = "relu"              # He normal
    RELU_UNIFORM = "relu_uniform"
    HE_NORMAL = "he_normal"
    HE_UNIFORM = "he_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    IDENTITY = "identity"

    @staticmethod
    def resolve(w) -> "WeightInit":
        if isinstance(w, WeightInit):
            return w
        if isinstance(w, str):
            return (WeightInit[w.upper()] if w.upper() in WeightInit.__members__
                    else WeightInit(w.lower()))
        raise ValueError(f"Cannot resolve weight init: {w!r}")


def init_weights(scheme, key, shape, fan_in: float, fan_out: float,
                 dtype=jnp.float32, gain: float = 1.0):
    """Draw a weight tensor per the scheme (reference: WeightInitUtil)."""
    w = WeightInit.resolve(scheme)
    if w is WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if w is WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if w is WeightInit.CONSTANT:
        return jnp.full(shape, gain, dtype)
    if w is WeightInit.NORMAL:
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if w is WeightInit.UNIFORM:
        a = math.sqrt(1.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if w is WeightInit.XAVIER:
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if w is WeightInit.XAVIER_UNIFORM:
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if w is WeightInit.XAVIER_FAN_IN:
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if w is WeightInit.LECUN_NORMAL:
        return math.sqrt(1.0 / fan_in) * jax.random.normal(key, shape, dtype)
    if w is WeightInit.LECUN_UNIFORM:
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if w in (WeightInit.RELU, WeightInit.HE_NORMAL):
        return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)
    if w in (WeightInit.RELU_UNIFORM, WeightInit.HE_UNIFORM):
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if w is WeightInit.SIGMOID_UNIFORM:
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if w is WeightInit.VAR_SCALING_NORMAL_FAN_IN:
        return math.sqrt(gain / fan_in) * jax.random.normal(key, shape, dtype)
    if w is WeightInit.VAR_SCALING_NORMAL_FAN_OUT:
        return math.sqrt(gain / fan_out) * jax.random.normal(key, shape, dtype)
    if w is WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
        return math.sqrt(2.0 * gain / (fan_in + fan_out)) * jax.random.normal(key, shape, dtype)
    if w is WeightInit.IDENTITY:
        if len(shape) == 2 and shape[0] == shape[1]:
            return jnp.eye(shape[0], dtype=dtype)
        raise ValueError("IDENTITY init requires square 2D shape")
    raise ValueError(f"Unhandled weight init: {w}")


__all__ = ["WeightInit", "init_weights"]
