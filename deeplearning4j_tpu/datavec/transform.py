"""TransformProcess: schema-aware, column-vectorized transform chains.

Reference: org/datavec/api/transform/TransformProcess.java (builder),
transform impls under org/datavec/api/transform/transform/**, filters
under transform/filter/**, conditions under transform/condition/**.

Redesign: the reference applies transforms record-at-a-time to Writable
lists. Here each step compiles to a vectorized numpy column operation —
the whole dataset flows as a dict {column: np.ndarray} ("table"), so a
TransformProcess over a million rows is a handful of numpy kernels, not
a million Python dispatches. Each step still carries exact output-schema
inference, and the builder verbs keep reference names.
"""

from __future__ import annotations

import datetime as _dt
import json
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema, _ColumnMeta

Table = Dict[str, np.ndarray]

#: reference: org.joda.time units used by TimeMathOpTransform
_TIME_UNIT_MS = {"MILLISECONDS": 1, "SECONDS": 1000,
                 "MINUTES": 60_000, "HOURS": 3_600_000,
                 "DAYS": 86_400_000}

#: reference: DeriveColumnsFromTimeTransform derived fields (Joda
#: conventions: dayOfWeek 1=Monday .. 7=Sunday)
_TIME_FIELDS = {
    "year": lambda d: d.year,
    "monthOfYear": lambda d: d.month,
    "dayOfMonth": lambda d: d.day,
    "dayOfWeek": lambda d: d.isoweekday(),
    "hourOfDay": lambda d: d.hour,
    "minuteOfHour": lambda d: d.minute,
    "secondOfMinute": lambda d: d.second,
}


# ---------------------------------------------------------------- conditions
class Condition:
    """Boolean predicate on a column, vectorized (reference:
    org/datavec/api/transform/condition/column/**)."""

    def __init__(self, column: str, op: str, value: Any = None,
                 values: Optional[Sequence] = None):
        self.column = column
        self.op = op
        self.value = value
        self.values = list(values) if values is not None else None

    def mask(self, table: Table) -> np.ndarray:
        col = table[self.column]
        if self.op == "LessThan":
            return col < self.value
        if self.op == "GreaterThan":
            return col > self.value
        if self.op == "LessOrEqual":
            return col <= self.value
        if self.op == "GreaterOrEqual":
            return col >= self.value
        if self.op == "Equal":
            return col == self.value
        if self.op == "NotEqual":
            return col != self.value
        if self.op == "InSet":
            return np.isin(col, self.values)
        if self.op == "NotInSet":
            return ~np.isin(col, self.values)
        raise ValueError(f"unknown condition op {self.op!r}")

    def to_dict(self):
        return {"column": self.column, "op": self.op,
                "value": self.value, "values": self.values}

    @staticmethod
    def from_dict(d):
        return Condition(d["column"], d["op"], d.get("value"), d.get("values"))


class ConditionOp:
    """Factory namespace mirroring reference ConditionOp usage."""
    @staticmethod
    def lessThan(column, v): return Condition(column, "LessThan", v)
    @staticmethod
    def greaterThan(column, v): return Condition(column, "GreaterThan", v)
    @staticmethod
    def equal(column, v): return Condition(column, "Equal", v)
    @staticmethod
    def notEqual(column, v): return Condition(column, "NotEqual", v)
    @staticmethod
    def inSet(column, vs): return Condition(column, "InSet", values=vs)


# ---------------------------------------------------------------- steps
class _Step:
    """One transform: output-schema inference + vectorized table fn."""

    def __init__(self, kind: str, params: Dict[str, Any]):
        self.kind = kind
        self.params = params

    def to_dict(self):
        p = dict(self.params)
        if isinstance(p.get("condition"), Condition):
            p["condition"] = p["condition"].to_dict()
        return {"kind": self.kind, "params": p}

    @staticmethod
    def from_dict(d):
        p = dict(d["params"])
        if "condition" in p and isinstance(p["condition"], dict):
            p["condition"] = Condition.from_dict(p["condition"])
        return _Step(d["kind"], p)

    # schema inference ------------------------------------------------
    def out_schema(self, s: Schema) -> Schema:
        k, p = self.kind, self.params
        cols = list(s.columns)
        if k == "removeColumns":
            drop = set(p["columns"])
            missing = drop - set(s.getColumnNames())
            if missing:
                raise KeyError(f"removeColumns: unknown {sorted(missing)}")
            return Schema([c for c in cols if c.name not in drop])
        if k == "removeAllColumnsExceptFor":
            keep = set(p["columns"])
            missing = keep - set(s.getColumnNames())
            if missing:
                raise KeyError(
                    f"removeAllColumnsExceptFor: unknown {sorted(missing)}")
            return Schema([c for c in cols if c.name in keep])
        if k == "renameColumn":
            if not s.hasColumn(p["old"]):
                raise KeyError(f"renameColumn: unknown column {p['old']!r}")
            out = []
            for c in cols:
                if c.name == p["old"]:
                    c = _ColumnMeta(p["new"], c.type, c.categories,
                                    c.min_value, c.max_value)
                out.append(c)
            return Schema(out)
        if k == "categoricalToInteger":
            out = []
            for c in cols:
                if c.name in p["columns"]:
                    if c.type != ColumnType.CATEGORICAL:
                        raise TypeError(f"{c.name} is {c.type}, not CATEGORICAL")
                    c = _ColumnMeta(c.name, ColumnType.INTEGER)
                out.append(c)
            return Schema(out)
        if k == "categoricalToOneHot":
            if not s.hasColumn(p["column"]):
                raise KeyError(
                    f"categoricalToOneHot: unknown column {p['column']!r}")
            out = []
            for c in cols:
                if c.name == p["column"]:
                    if c.type != ColumnType.CATEGORICAL:
                        raise TypeError(f"{c.name} is {c.type}, "
                                        "not CATEGORICAL")
                    for cat in c.categories:
                        out.append(_ColumnMeta(f"{c.name}[{cat}]",
                                               ColumnType.INTEGER))
                else:
                    out.append(c)
            return Schema(out)
        if k in ("integerToCategorical", "stringToCategorical"):
            if not s.hasColumn(p["column"]):
                raise KeyError(f"{k}: unknown column {p['column']!r}")
            out = []
            for c in cols:
                if c.name == p["column"]:
                    c = _ColumnMeta(c.name, ColumnType.CATEGORICAL,
                                    p["categories"])
                out.append(c)
            return Schema(out)
        if k in ("doubleMathOp", "doubleColumnsMathOp", "normalize",
                 "replaceString", "filter", "conditionalReplaceValue",
                 "custom"):
            if k == "doubleColumnsMathOp":
                return Schema(cols + [_ColumnMeta(p["new_column"],
                                                  ColumnType.DOUBLE)])
            return s
        # ---- time steps (reference: transform/transform/time/**) ----
        if k == "stringToTime":
            name = p["column"]
            if not s.hasColumn(name):
                raise KeyError(f"stringToTime: unknown column {name!r}")
            if s.getColumnMeta(name).type != ColumnType.STRING:
                raise TypeError(
                    f"stringToTime: {name} is "
                    f"{s.getColumnMeta(name).type}, not STRING")
            return Schema([_ColumnMeta(c.name, ColumnType.TIME)
                           if c.name == name else c for c in cols])
        if k == "timeMathOp":
            name = p["column"]
            if not s.hasColumn(name):
                raise KeyError(f"timeMathOp: unknown column {name!r}")
            if s.getColumnMeta(name).type != ColumnType.TIME:
                raise TypeError(
                    f"timeMathOp: {name} is "
                    f"{s.getColumnMeta(name).type}, not TIME")
            if p["unit"] not in _TIME_UNIT_MS:
                raise ValueError(f"timeMathOp: unknown unit {p['unit']!r}")
            if p["op"] not in ("Add", "Subtract"):
                # validated here (not only in the Builder) so foreign
                # JSON via fromJson cannot smuggle a silent Subtract
                raise ValueError(f"timeMathOp: op must be Add|Subtract, "
                                 f"got {p['op']!r}")
            return s
        if k == "deriveColumnsFromTime":
            name = p["column"]
            if not s.hasColumn(name):
                raise KeyError(
                    f"deriveColumnsFromTime: unknown column {name!r}")
            if s.getColumnMeta(name).type != ColumnType.TIME:
                raise TypeError(
                    f"deriveColumnsFromTime: {name} is "
                    f"{s.getColumnMeta(name).type}, not TIME")
            taken = set(s.getColumnNames())
            for d in p["derived"]:
                if d["field"] not in _TIME_FIELDS:
                    raise ValueError(
                        f"deriveColumnsFromTime: unknown field "
                        f"{d['field']!r} (know {sorted(_TIME_FIELDS)})")
                if d["name"] in taken:
                    raise ValueError(
                        f"deriveColumnsFromTime: derived column "
                        f"{d['name']!r} collides with an existing "
                        "column")
                taken.add(d["name"])
            extra = [_ColumnMeta(d["name"], ColumnType.INTEGER)
                     for d in p["derived"]]
            return Schema(cols + extra)
        # ---- sequence steps (reference: transform/sequence/**) ----
        if k == "convertToSequence":
            for c in (p["key_column"], p["sort_column"]):
                if not s.hasColumn(c):
                    raise KeyError(f"convertToSequence: unknown column "
                                   f"{c!r}")
            return s
        if k == "offsetSequence":
            for c in p["columns"]:
                if not s.hasColumn(c):
                    raise KeyError(f"offsetSequence: unknown column {c!r}")
            if p.get("op", "InPlace") == "NewColumn":
                extra = [_ColumnMeta(f"{c}_offset{p['offset']}",
                                     ColumnType.DOUBLE)
                         for c in p["columns"]]
                return Schema(cols + extra)
            return s
        if k == "sequenceMovingWindowReduce":
            if not s.hasColumn(p["column"]):
                raise KeyError("sequenceMovingWindowReduce: unknown "
                               f"column {p['column']!r}")
            new = f"{p['column']}[{p['op'].lower()},{p['window']}]"
            return Schema(cols + [_ColumnMeta(new, ColumnType.DOUBLE)])
        if k == "sequenceDifference":
            if not s.hasColumn(p["column"]):
                raise KeyError(f"sequenceDifference: unknown column "
                               f"{p['column']!r}")
            return s
        if k == "trimSequence":
            return s
        raise ValueError(f"unknown step kind {k!r}")

    #: step kinds that operate on ONE SEQUENCE's table at a time
    SEQUENCE_KINDS = frozenset({"offsetSequence",
                                "sequenceMovingWindowReduce",
                                "sequenceDifference", "trimSequence"})

    # execution -------------------------------------------------------
    def apply(self, table: Table, s: Schema) -> Table:
        k, p = self.kind, self.params
        if k == "removeColumns":
            return {n: v for n, v in table.items() if n not in set(p["columns"])}
        if k == "removeAllColumnsExceptFor":
            return {n: table[n] for n in table if n in set(p["columns"])}
        if k == "renameColumn":
            return {(p["new"] if n == p["old"] else n): v
                    for n, v in table.items()}
        if k == "categoricalToInteger":
            out = dict(table)
            for name in p["columns"]:
                cats = s.getColumnMeta(name).categories
                lut = {c: i for i, c in enumerate(cats)}
                out[name] = np.array([lut[v] for v in table[name]],
                                     dtype=np.int64)
            return out
        if k == "categoricalToOneHot":
            name = p["column"]
            cats = s.getColumnMeta(name).categories
            out = {}
            for n, v in table.items():
                if n == name:
                    for cat in cats:
                        out[f"{name}[{cat}]"] = (v == cat).astype(np.int64)
                else:
                    out[n] = v
            return out
        if k == "integerToCategorical":
            name, cats = p["column"], p["categories"]
            out = dict(table)
            out[name] = np.array([cats[int(v)] for v in table[name]],
                                 dtype=object)
            return out
        if k == "stringToCategorical":
            return dict(table)  # type-only change
        if k == "doubleMathOp":
            name, op, v = p["column"], p["op"], p["value"]
            col = table[name].astype(np.float64)
            fns = {"Add": lambda: col + v, "Subtract": lambda: col - v,
                   "Multiply": lambda: col * v, "Divide": lambda: col / v,
                   "Modulus": lambda: col % v,
                   "ScalarMax": lambda: np.maximum(col, v),
                   "ScalarMin": lambda: np.minimum(col, v),
                   "ReverseSubtract": lambda: v - col,
                   "ReverseDivide": lambda: v / col}
            out = dict(table)
            out[name] = fns[op]()
            return out
        if k == "doubleColumnsMathOp":
            op = p["op"]
            acc = table[p["columns"][0]].astype(np.float64).copy()
            for n in p["columns"][1:]:
                c = table[n].astype(np.float64)
                if op == "Add":
                    acc = acc + c
                elif op == "Subtract":
                    acc = acc - c
                elif op == "Multiply":
                    acc = acc * c
                elif op == "Divide":
                    acc = acc / c
                else:
                    raise ValueError(op)
            out = dict(table)
            out[p["new_column"]] = acc
            return out
        if k == "normalize":
            name, kind = p["column"], p["type"]
            col = table[name].astype(np.float64)
            if kind == "MinMax":
                lo, hi = col.min(), col.max()
                col = (col - lo) / (hi - lo) if hi > lo else col * 0.0
            elif kind == "Standardize":
                mu, sd = col.mean(), col.std()
                col = (col - mu) / sd if sd > 0 else col - mu
            else:
                raise ValueError(kind)
            out = dict(table)
            out[name] = col
            return out
        if k == "replaceString":
            name = p["column"]
            out = dict(table)
            out[name] = np.array([str(v).replace(p["search"], p["replace"])
                                  for v in table[name]], dtype=object)
            return out
        if k == "filter":
            # reference ConditionFilter REMOVES rows matching the condition
            keep = ~p["condition"].mask(table)
            return {n: v[keep] for n, v in table.items()}
        if k == "conditionalReplaceValue":
            m = p["condition"].mask(table)
            out = dict(table)
            col = table[p["column"]].copy()
            col[m] = p["value"]
            out[p["column"]] = col
            return out
        if k == "custom":
            return p["fn"](dict(table))
        if k == "stringToTime":
            name, fmt = p["column"], p["format"]

            def to_ms(v):
                d = _dt.datetime.strptime(str(v), fmt)
                if d.tzinfo is None:          # naive -> interpret UTC;
                    d = d.replace(tzinfo=_dt.timezone.utc)
                return int(d.timestamp() * 1000)   # %z offsets honored

            out = dict(table)
            out[name] = np.array([to_ms(v) for v in table[name]],
                                 dtype=np.int64)
            return out
        if k == "timeMathOp":
            name = p["column"]
            delta = int(p["value"]) * _TIME_UNIT_MS[p["unit"]]
            col = table[name].astype(np.int64)
            out = dict(table)
            out[name] = col + delta if p["op"] == "Add" else col - delta
            return out
        if k == "deriveColumnsFromTime":
            name = p["column"]
            out = dict(table)
            dts = [_dt.datetime.fromtimestamp(int(v) / 1000.0,
                                              _dt.timezone.utc)
                   for v in table[name]]
            for d in p["derived"]:
                out[d["name"]] = np.array(
                    [_TIME_FIELDS[d["field"]](x) for x in dts],
                    dtype=np.int64)
            return out
        if k == "convertToSequence":
            return dict(table)  # grouping handled by TransformProcess
        if k in _Step.SEQUENCE_KINDS:
            return self.apply_seq(table, s)
        raise ValueError(f"unknown step kind {k!r}")

    def apply_seq(self, table: Table, s: Schema) -> Table:
        """Apply a sequence step to ONE sequence's table (rows = time
        steps, in order). Reference: transform/sequence/** —
        OffsetSequenceTransform, SequenceMovingWindowReduceTransform,
        SequenceDifferenceTransform, sequence trim."""
        k, p = self.kind, self.params
        n = len(next(iter(table.values()))) if table else 0
        if k == "offsetSequence":
            # positive offset = lag: value at step t comes from t-offset;
            # steps lacking a source row are TRIMMED from the sequence
            off = int(p["offset"])
            new_col = p.get("op", "InPlace") == "NewColumn"
            out = dict(table)
            lo = min(max(0, off), n)        # clamp to the sequence
            hi = max(n + min(0, off), lo)   # empty window, not negative
            for c in p["columns"]:
                src = table[c]
                shifted = src[lo - off:hi - off] if hi > lo else src[:0]
                if new_col:
                    out[f"{c}_offset{off}"] = shifted.astype(np.float64)
                else:
                    out[c] = shifted
            # trim every other column to the surviving window (a
            # fully-trimmed sequence still carries ALL schema columns,
            # as length-0 arrays — downstream steps index them)
            for c in out:
                if len(out[c]) != hi - lo:
                    out[c] = out[c][lo:hi]
            return out
        if k == "sequenceMovingWindowReduce":
            col = table[p["column"]].astype(np.float64)
            w = int(p["window"])
            fns = {"Mean": np.mean, "Sum": np.sum, "Min": np.min,
                   "Max": np.max, "Stdev": np.std}
            fn = fns[p["op"]]
            if n >= w > 0:
                # vectorized trailing windows; the first w-1 steps use
                # partial (shorter) windows
                full = fn(np.lib.stride_tricks.sliding_window_view(
                    col, w), axis=-1)
                head = np.array([fn(col[:t + 1]) for t in range(w - 1)])
                red = np.concatenate([head, full])
            else:
                red = np.array([fn(col[max(0, t - w + 1):t + 1])
                                for t in range(n)])
            out = dict(table)
            out[f"{p['column']}[{p['op'].lower()},{w}]"] = red
            return out
        if k == "sequenceDifference":
            lag = int(p.get("lag", 1))
            col = table[p["column"]].astype(np.float64)
            d = np.zeros_like(col)
            if n > lag:
                d[lag:] = col[lag:] - col[:-lag]
            out = dict(table)
            out[p["column"]] = d
            return out
        if k == "trimSequence":
            m = int(p["num_steps"])
            sl = slice(m, None) if p.get("from_start", True) \
                else slice(None, max(0, n - m))
            return {c: v[sl] for c, v in table.items()}
        raise ValueError(f"not a sequence step: {k!r}")


# ---------------------------------------------------------------- process
class TransformProcess:
    """Chain of schema-checked vectorized steps (reference builder API)."""

    def __init__(self, initial_schema: Schema, steps: Sequence[_Step] = ()):
        self.initial_schema = initial_schema
        self.steps = list(steps)
        self.final_schema = self._infer()
        self._convert_index()  # validate sequence-step ordering early

    def getFinalSchema(self) -> Schema:
        """reference: TransformProcess#getFinalSchema."""
        return self.final_schema

    def _infer(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.out_schema(s)
        return s

    def _convert_index(self):
        idx = [i for i, st in enumerate(self.steps)
               if st.kind == "convertToSequence"]
        if len(idx) > 1:
            raise ValueError("at most one convertToSequence per process")
        ci = idx[0] if idx else None
        if ci is not None:
            early = [st.kind for st in self.steps[:ci]
                     if st.kind in _Step.SEQUENCE_KINDS]
            if early:
                raise ValueError(
                    f"sequence steps {early} appear BEFORE "
                    "convertToSequence — they would run on the flat "
                    "ungrouped table; move them after the conversion")
        return ci

    # execution over records or a columnar table
    def execute(self, records: Sequence[Sequence]):
        """Flat records in; flat records out — or, when the chain has a
        convertToSequence step (reference semantics), a LIST OF
        SEQUENCES out (each a list of per-timestep records)."""
        ci = self._convert_index()
        if ci is None:
            if any(st.kind in _Step.SEQUENCE_KINDS for st in self.steps):
                raise ValueError(
                    "chain contains sequence steps but no "
                    "convertToSequence — use executeSequences() on "
                    "already-grouped sequences, or add "
                    "convertToSequence(key, sort)")
            table = self.executeColumnar(self._to_table(records))
            return self._rows(table, self.final_schema)
        # flat prefix -> group by key (ordered by sort col) -> per-seq
        s = self.initial_schema
        table = self._to_table(records)
        for st in self.steps[:ci]:
            table = st.apply(table, s)
            s = st.out_schema(s)
        key_c = self.steps[ci].params["key_column"]
        sort_c = self.steps[ci].params["sort_column"]
        keys = np.asarray(table[key_c])
        if keys.dtype.kind == "f" and np.isnan(keys).any():
            raise ValueError(
                f"convertToSequence: key column {key_c!r} contains NaN "
                "— NaN keys cannot be grouped; clean or filter them "
                "first")
        # one vectorized grouping pass (not one scan per key):
        # unique+inverse labels every row, argsort over labels groups
        # them, and first-seen order is restored from first indices
        uniq, first_idx, inv = np.unique(keys, return_index=True,
                                         return_inverse=True)
        seen_rank = np.argsort(np.argsort(first_idx))  # uniq -> order
        order = np.lexsort((np.arange(len(keys)), seen_rank[inv]))
        bounds = np.flatnonzero(np.diff(seen_rank[inv][order],
                                        prepend=-1))
        out = []
        for gi in range(len(uniq)):
            rows = order[bounds[gi]:
                         bounds[gi + 1] if gi + 1 < len(uniq) else None]
            seq = {c: np.asarray(v)[rows] for c, v in table.items()}
            so = np.argsort(seq[sort_c], kind="stable")
            seq = {c: v[so] for c, v in seq.items()}
            s2 = s
            for st in self.steps[ci + 1:]:
                seq = st.apply(seq, s2)
                s2 = st.out_schema(s2)
            out.append(self._rows(seq, self.final_schema))
        return out

    def executeSequences(self, sequences):
        """Apply the whole chain to each already-grouped sequence
        (list of per-timestep records). The chain must not contain
        convertToSequence."""
        if self._convert_index() is not None:
            raise ValueError("executeSequences: chain already groups via "
                             "convertToSequence — use execute() on flat "
                             "records")
        out = []
        for seq in sequences:
            table = self.executeColumnar(self._to_table(seq))
            out.append(self._rows(table, self.final_schema))
        return out

    def _rows(self, table: Table, schema: Schema) -> List[List]:
        names = schema.getColumnNames()
        n = len(next(iter(table.values()))) if table else 0
        return [[table[c][i] for c in names] for i in range(n)]

    def executeColumnar(self, table: Table) -> Table:
        """Apply the chain to one columnar table. Sequence steps treat
        the WHOLE table as a single ordered sequence (this is how
        executeSequences drives each sequence); a chain that needs
        grouping (convertToSequence) must go through execute()."""
        if self._convert_index() is not None:
            raise ValueError(
                "chain contains convertToSequence — grouped execution "
                "is required; use execute() on flat records")
        s = self.initial_schema
        for st in self.steps:
            table = st.apply(table, s)
            s = st.out_schema(s)
        return table

    def executeToArray(self, records: Sequence[Sequence]) -> np.ndarray:
        """Run + pack all (numeric) final columns into a float32 matrix —
        the handoff point to the accelerator."""
        table = self.executeColumnar(self._to_table(records))
        cols = []
        for c in self.final_schema.columns:
            if not c.type.numeric:
                raise TypeError(
                    f"column {c.name!r} is {c.type.value}, not numeric; "
                    "convert (categoricalToInteger/OneHot) before packing")
            cols.append(np.asarray(table[c.name], dtype=np.float32))
        return np.stack(cols, axis=1) if cols else np.zeros((0, 0), np.float32)

    def _to_table(self, records: Sequence[Sequence]) -> Table:
        names = self.initial_schema.getColumnNames()
        cols: Table = {}
        arr = list(records)
        for j, name in enumerate(names):
            vals = [r[j] for r in arr]
            meta = self.initial_schema.columns[j]
            if meta.type.numeric:
                cols[name] = np.asarray(vals, dtype=np.float64)
            else:
                cols[name] = np.array(vals, dtype=object)
        return cols

    # serde (reference: TransformProcess#toJson/fromJson)
    def toJson(self) -> str:
        bad = [s for s in self.steps if s.kind == "custom"]
        if bad:
            raise ValueError(
                "TransformProcess contains custom (non-serializable) "
                "transform steps; remove .transform(fn) steps before "
                "toJson()")
        return json.dumps({
            "initialSchema": json.loads(self.initial_schema.toJson()),
            "steps": [s.to_dict() for s in self.steps],
        }, indent=2)

    @staticmethod
    def fromJson(s: str) -> "TransformProcess":
        d = json.loads(s)
        schema = Schema.fromJson(json.dumps(d["initialSchema"]))
        return TransformProcess(schema,
                                [_Step.from_dict(x) for x in d["steps"]])

    # ---- builder ----
    class Builder:
        def __init__(self, initial_schema: Schema):
            self._schema = initial_schema
            self._steps: List[_Step] = []

        def _add(self, kind, **params):
            self._steps.append(_Step(kind, params))
            return self

        def removeColumns(self, *columns: str):
            return self._add("removeColumns", columns=list(columns))

        def removeAllColumnsExceptFor(self, *columns: str):
            return self._add("removeAllColumnsExceptFor", columns=list(columns))

        def renameColumn(self, old: str, new: str):
            return self._add("renameColumn", old=old, new=new)

        def categoricalToInteger(self, *columns: str):
            return self._add("categoricalToInteger", columns=list(columns))

        def categoricalToOneHot(self, column: str):
            return self._add("categoricalToOneHot", column=column)

        def integerToCategorical(self, column: str, categories: Sequence[str]):
            return self._add("integerToCategorical", column=column,
                             categories=list(categories))

        def stringToCategorical(self, column: str, categories: Sequence[str]):
            return self._add("stringToCategorical", column=column,
                             categories=list(categories))

        def doubleMathOp(self, column: str, op: str, value: float):
            return self._add("doubleMathOp", column=column, op=op, value=value)

        def doubleColumnsMathOp(self, new_column: str, op: str,
                                *columns: str):
            return self._add("doubleColumnsMathOp", new_column=new_column,
                             op=op, columns=list(columns))

        def normalize(self, column: str, type: str = "Standardize"):
            return self._add("normalize", column=column, type=type)

        def replaceStringTransform(self, column: str, search: str,
                                   replace: str):
            return self._add("replaceString", column=column, search=search,
                             replace=replace)

        def filter(self, condition: Condition):
            """Remove rows MATCHING the condition (reference
            ConditionFilter semantics)."""
            return self._add("filter", condition=condition)

        def conditionalReplaceValueTransform(self, column: str, value,
                                             condition: Condition):
            return self._add("conditionalReplaceValue", column=column,
                             value=value, condition=condition)

        # ---- sequence ops (reference: transform/sequence/**) ----
        def stringToTimeTransform(self, column: str, format: str):
            """Parse datetime strings to epoch-millis TIME (reference:
            StringToTimeTransform; format is a Python strptime pattern
            — e.g. the reference's 'YYYY-MM-dd HH:mm:ss' is
            '%Y-%m-%d %H:%M:%S'). Timestamps are interpreted UTC."""
            return self._add("stringToTime", column=column,
                             format=format)

        def timeMathOp(self, column: str, op: str, value: int,
                       unit: str = "MILLISECONDS"):
            """Shift a TIME column (reference: TimeMathOpTransform;
            op Add/Subtract, unit MILLISECONDS..DAYS)."""
            if op not in ("Add", "Subtract"):
                raise ValueError("timeMathOp op must be Add|Subtract")
            return self._add("timeMathOp", column=column, op=op,
                             value=value, unit=unit)

        def deriveColumnsFromTime(self, column: str, *derived):
            """Derive integer fields from a TIME column (reference:
            DeriveColumnsFromTimeTransform.Builder). Each derived spec
            is (new_name, field) with field in year/monthOfYear/
            dayOfMonth/dayOfWeek/hourOfDay/minuteOfHour/
            secondOfMinute."""
            return self._add(
                "deriveColumnsFromTime", column=column,
                derived=[{"name": n, "field": f} for n, f in derived])

        def convertToSequence(self, key_column: str, sort_column: str):
            """Group flat records into per-key sequences ordered by
            sort_column (reference: TransformProcess.Builder
            #convertToSequence)."""
            return self._add("convertToSequence", key_column=key_column,
                             sort_column=sort_column)

        def offsetSequence(self, columns, offset: int, op: str = "InPlace"):
            """Shift columns in time by ``offset`` steps (positive =
            lag). Steps without a source row are trimmed. op:
            "InPlace" or "NewColumn" (adds ``{col}_offset{n}``)."""
            if op not in ("InPlace", "NewColumn"):
                raise ValueError(f"offsetSequence op {op!r}")
            return self._add("offsetSequence", columns=list(columns),
                             offset=int(offset), op=op)

        def sequenceMovingWindowReduce(self, column: str, window: int,
                                       op: str = "Mean"):
            """Trailing-window rolling reduce -> new column
            ``{column}[{op},{window}]`` (partial leading windows)."""
            if op not in ("Mean", "Sum", "Min", "Max", "Stdev"):
                raise ValueError(
                    f"sequenceMovingWindowReduce op {op!r} (use "
                    "Mean/Sum/Min/Max/Stdev)")
            if int(window) < 1:
                raise ValueError(f"window must be >= 1, got {window}")
            return self._add("sequenceMovingWindowReduce", column=column,
                             window=int(window), op=op)

        def sequenceDifference(self, column: str, lag: int = 1):
            """x_t - x_{t-lag} in place; the first ``lag`` steps become
            0 (reference SequenceDifferenceTransform default mode)."""
            if int(lag) < 1:
                raise ValueError(f"sequenceDifference lag must be >= 1, "
                                 f"got {lag}")
            return self._add("sequenceDifference", column=column,
                             lag=int(lag))

        def trimSequence(self, num_steps: int, from_start: bool = True):
            return self._add("trimSequence", num_steps=int(num_steps),
                             from_start=bool(from_start))

        def transform(self, fn: Callable[[Table], Table]):
            """Escape hatch: arbitrary vectorized table→table fn (not
            JSON-serializable)."""
            return self._add("custom", fn=fn)

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, self._steps)
