"""DataVec-equivalent ETL layer (reference: datavec/ — SURVEY.md §2.25-2.26).

The reference pipeline is record-at-a-time Java objects (Writable lists
flowing RecordReader → TransformProcess → RecordReaderDataSetIterator).
The TPU-native redesign is *column-vectorized*: readers parse whole
files into numpy column arrays once, and a TransformProcess compiles to
a chain of vectorized numpy column ops, because host-side ETL must keep
an accelerator fed — per-record Python objects cannot. The public
surface (Schema, TransformProcess builder verbs, RecordReader
next/hasNext) mirrors the reference so pipelines translate 1:1.
"""

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    FileSplit,
    LineRecordReader,
    NumberedFileInputSplit,
    RecordReader,
)
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.image import (
    ImageRecordReader,
    NativeImageLoader,
    ParentPathLabelGenerator,
)

from deeplearning4j_tpu.datavec.analysis import (
    AnalyzeLocal, DataAnalysis, DataQualityAnalysis,
)
from deeplearning4j_tpu.datavec.join import Join, JoinType, Reducer, ReduceOp

__all__ = [
    "ColumnType", "Schema", "TransformProcess",
    "AnalyzeLocal", "DataAnalysis", "DataQualityAnalysis",
    "Join", "JoinType", "Reducer", "ReduceOp",
    "RecordReader", "CSVRecordReader", "CSVSequenceRecordReader",
    "LineRecordReader", "CollectionRecordReader",
    "FileSplit", "NumberedFileInputSplit",
    "ImageRecordReader", "NativeImageLoader", "ParentPathLabelGenerator",
]
