"""Schema: typed column metadata for tabular records.

Reference: org/datavec/api/transform/schema/Schema.java (builder with
addColumnInteger/Double/Categorical/String/Time). JSON round-trip kept
(reference guarantees Jackson round-trip for all transform configs).
"""

from __future__ import annotations

import enum
import json
from typing import Any, Dict, List, Optional, Sequence


class ColumnType(enum.Enum):
    INTEGER = "Integer"
    LONG = "Long"
    DOUBLE = "Double"
    FLOAT = "Float"
    CATEGORICAL = "Categorical"
    STRING = "String"
    TIME = "Time"
    BOOLEAN = "Boolean"

    @property
    def numeric(self) -> bool:
        return self in (ColumnType.INTEGER, ColumnType.LONG,
                        ColumnType.DOUBLE, ColumnType.FLOAT,
                        ColumnType.BOOLEAN, ColumnType.TIME)


class _ColumnMeta:
    def __init__(self, name: str, ctype: ColumnType,
                 categories: Optional[List[str]] = None,
                 min_value=None, max_value=None):
        self.name = name
        self.type = ctype
        self.categories = list(categories) if categories else None
        self.min_value = min_value
        self.max_value = max_value

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "type": self.type.value}
        if self.categories is not None:
            d["categories"] = self.categories
        if self.min_value is not None:
            d["min"] = self.min_value
        if self.max_value is not None:
            d["max"] = self.max_value
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "_ColumnMeta":
        return _ColumnMeta(d["name"], ColumnType(d["type"]),
                           d.get("categories"), d.get("min"), d.get("max"))


class Schema:
    """Immutable-ish ordered column schema with a reference-style Builder."""

    def __init__(self, columns: Sequence[_ColumnMeta] = ()):
        self.columns: List[_ColumnMeta] = list(columns)

    # ---- queries (reference API names) ----
    def numColumns(self) -> int:
        return len(self.columns)

    def getColumnNames(self) -> List[str]:
        return [c.name for c in self.columns]

    def getColumnTypes(self) -> List[ColumnType]:
        return [c.type for c in self.columns]

    def getIndexOfColumn(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column named {name!r}; have {self.getColumnNames()}")

    def getColumnMeta(self, name: str) -> _ColumnMeta:
        return self.columns[self.getIndexOfColumn(name)]

    def hasColumn(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    # ---- serde ----
    def toJson(self) -> str:
        return json.dumps({"columns": [c.to_dict() for c in self.columns]},
                          indent=2)

    @staticmethod
    def fromJson(s: str) -> "Schema":
        d = json.loads(s)
        return Schema([_ColumnMeta.from_dict(c) for c in d["columns"]])

    def __eq__(self, other):
        return (isinstance(other, Schema)
                and self.toJson() == other.toJson())

    def __repr__(self):
        cols = ", ".join(f"{c.name}:{c.type.value}" for c in self.columns)
        return f"Schema({cols})"

    # ---- builder ----
    class Builder:
        def __init__(self):
            self._cols: List[_ColumnMeta] = []

        def addColumnMeta(self, meta: "_ColumnMeta") -> "Schema.Builder":
            """Append a COPY of an existing column meta (never aliases
            the source schema's mutable metadata)."""
            self._cols.append(_ColumnMeta.from_dict(meta.to_dict()))
            return self

        def addColumnInteger(self, name: str, min_value=None, max_value=None):
            self._cols.append(_ColumnMeta(name, ColumnType.INTEGER,
                                          None, min_value, max_value))
            return self

        def addColumnLong(self, name: str):
            self._cols.append(_ColumnMeta(name, ColumnType.LONG))
            return self

        def addColumnDouble(self, name: str, min_value=None, max_value=None):
            self._cols.append(_ColumnMeta(name, ColumnType.DOUBLE,
                                          None, min_value, max_value))
            return self

        def addColumnFloat(self, name: str):
            self._cols.append(_ColumnMeta(name, ColumnType.FLOAT))
            return self

        def addColumnCategorical(self, name: str, *categories: str):
            if len(categories) == 1 and isinstance(categories[0], (list, tuple)):
                categories = tuple(categories[0])
            self._cols.append(_ColumnMeta(name, ColumnType.CATEGORICAL,
                                          list(categories)))
            return self

        def addColumnString(self, name: str):
            self._cols.append(_ColumnMeta(name, ColumnType.STRING))
            return self

        def addColumnTime(self, name: str):
            self._cols.append(_ColumnMeta(name, ColumnType.TIME))
            return self

        def addColumnBoolean(self, name: str):
            self._cols.append(_ColumnMeta(name, ColumnType.BOOLEAN))
            return self

        def addColumnsDouble(self, *names: str):
            for n in names:
                self.addColumnDouble(n)
            return self

        def build(self) -> "Schema":
            names = [c.name for c in self._cols]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate column names: {names}")
            return Schema(self._cols)
