"""Data analysis + quality (reference: org/datavec/api/transform/analysis
— AnalyzeLocal.analyze / analyzeQuality, DataAnalysis with per-column
{Integer,Double,Categorical,String}Analysis, and DataQualityAnalysis).

Columnar numpy implementation: one pass over each column computes the
reference's reported statistics (min/max/mean/stdev/count for numeric
columns, unique counts for categoricals, length stats for strings) and
quality counts (missing/NaN/invalid-type entries).
"""

from __future__ import annotations

import dataclasses
import json
import math
from collections import Counter
from typing import Any, Dict, List, Sequence

import numpy as np

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema


@dataclasses.dataclass
class NumericalColumnAnalysis:
    """Reference: IntegerAnalysis / DoubleAnalysis."""

    count: int
    min: float
    max: float
    mean: float
    stdev: float
    count_zero: int
    count_negative: int
    count_positive: int

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CategoricalColumnAnalysis:
    """Reference: CategoricalAnalysis — per-category counts."""

    count: int
    unique_count: int
    category_counts: Dict[str, int]

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class StringColumnAnalysis:
    """Reference: StringAnalysis — length statistics."""

    count: int
    min_length: int
    max_length: int
    mean_length: float

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ColumnQuality:
    """Reference: DataQualityAnalysis per-column counts."""

    valid: int
    invalid: int
    missing: int

    def to_dict(self):
        return dataclasses.asdict(self)


class DataAnalysis:
    """Reference: org/datavec/api/transform/analysis/DataAnalysis —
    schema + per-column analysis, printable + JSON round-trip."""

    def __init__(self, schema: Schema, columns: Dict[str, Any]):
        self.schema = schema
        self.columns = columns

    def getColumnAnalysis(self, name: str):
        return self.columns[name]

    def toJson(self) -> str:
        return json.dumps({k: v.to_dict() for k, v in self.columns.items()},
                          indent=2, default=str)

    def __str__(self):
        lines = ["DataAnalysis:"]
        for name, a in self.columns.items():
            lines.append(f"  {name}: {a.to_dict()}")
        return "\n".join(lines)


class DataQualityAnalysis:
    def __init__(self, columns: Dict[str, ColumnQuality]):
        self.columns = columns

    def getColumnQuality(self, name: str) -> ColumnQuality:
        return self.columns[name]

    def __str__(self):
        return "\n".join(f"{k}: {v.to_dict()}" for k, v in
                         self.columns.items())


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and math.isnan(v):
        return True
    if isinstance(v, str) and v.strip() == "":
        return True
    return False


class AnalyzeLocal:
    """Reference: org/datavec/local/transforms/AnalyzeLocal (single-
    process analog of the Spark AnalyzeSpark)."""

    @staticmethod
    def analyze(schema: Schema, records: Sequence[Sequence]) -> DataAnalysis:
        cols: Dict[str, Any] = {}
        for ci, name in enumerate(schema.getColumnNames()):
            meta = schema.getColumnMeta(name)
            values = [r[ci] for r in records if not _is_missing(r[ci])]
            if meta.type.numeric:
                # skip unparsable cells — analyzeQuality counts them as
                # invalid; analyze() must survive dirty CSV data
                nums = []
                for v in values:
                    try:
                        f = float(v)
                    except (TypeError, ValueError):
                        continue
                    if math.isfinite(f):  # a literal "nan"/"inf" cell
                        nums.append(f)    # must not poison min/max/mean
                arr = np.asarray(nums, np.float64)
                n = arr.size
                cols[name] = NumericalColumnAnalysis(
                    count=n,
                    min=float(arr.min()) if n else float("nan"),
                    max=float(arr.max()) if n else float("nan"),
                    mean=float(arr.mean()) if n else float("nan"),
                    stdev=float(arr.std(ddof=1)) if n > 1 else 0.0,
                    count_zero=int((arr == 0).sum()),
                    count_negative=int((arr < 0).sum()),
                    count_positive=int((arr > 0).sum()))
            elif meta.type == ColumnType.CATEGORICAL:
                c = Counter(str(v) for v in values)
                cols[name] = CategoricalColumnAnalysis(
                    count=len(values), unique_count=len(c),
                    category_counts=dict(c))
            else:  # STRING
                lens = [len(str(v)) for v in values]
                cols[name] = StringColumnAnalysis(
                    count=len(values),
                    min_length=min(lens) if lens else 0,
                    max_length=max(lens) if lens else 0,
                    mean_length=(sum(lens) / len(lens)) if lens else 0.0)
        return DataAnalysis(schema, cols)

    @staticmethod
    def analyzeQuality(schema: Schema,
                       records: Sequence[Sequence]) -> DataQualityAnalysis:
        out: Dict[str, ColumnQuality] = {}
        for ci, name in enumerate(schema.getColumnNames()):
            meta = schema.getColumnMeta(name)
            valid = invalid = missing = 0
            for r in records:
                v = r[ci]
                if _is_missing(v):
                    missing += 1
                    continue
                if meta.type.numeric:
                    try:
                        if math.isfinite(float(v)):
                            valid += 1
                        else:
                            invalid += 1
                    except (TypeError, ValueError):
                        invalid += 1
                elif meta.type == ColumnType.CATEGORICAL:
                    allowed = getattr(meta, "categories", None)
                    if allowed and str(v) not in allowed:
                        invalid += 1
                    else:
                        valid += 1
                else:
                    valid += 1
            out[name] = ColumnQuality(valid=valid, invalid=invalid,
                                      missing=missing)
        return DataQualityAnalysis(out)
