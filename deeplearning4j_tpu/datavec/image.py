"""Image ETL: loader, record reader, label generators, transforms.

Reference: datavec-data-image — NativeImageLoader.java (JavaCV/OpenCV
native decode → CHW float INDArray), ImageRecordReader.java,
ParentPathLabelGenerator.java, transforms under org/datavec/image/
transform/** (ResizeImageTransform, FlipImageTransform, CropImage...).

TPU redesign: decode on host via PIL into **NHWC** numpy (TPU conv
layout; the reference uses NCHW for cuDNN), batch-stack, and hand the
accelerator one contiguous array. Augmentation transforms are
vectorized numpy where possible.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.datavec.records import (FileSplit, InputSplit,
                                                RecordReader, _as_split)


class ImageTransform:
    """Composable image transform (reference: ImageTransform chain)."""

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, img, rng):
        from PIL import Image
        pil = Image.fromarray(img.astype(np.uint8))
        return np.asarray(pil.resize((self.w, self.h), Image.BILINEAR))


class FlipImageTransform(ImageTransform):
    """Random horizontal flip with probability p."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng):
        return img[:, ::-1] if rng.random() < self.p else img


class CropImageTransform(ImageTransform):
    """Random crop by up to ``margin`` pixels per side, then pad back."""

    def __init__(self, margin: int):
        self.margin = margin

    def __call__(self, img, rng):
        h, w = img.shape[:2]
        t = int(rng.integers(0, self.margin + 1))
        l = int(rng.integers(0, self.margin + 1))
        b = int(rng.integers(0, self.margin + 1))
        r = int(rng.integers(0, self.margin + 1))
        cropped = img[t:h - b or h, l:w - r or w]
        from PIL import Image
        pil = Image.fromarray(cropped.astype(np.uint8))
        return np.asarray(pil.resize((w, h), Image.BILINEAR))


class PipelineImageTransform(ImageTransform):
    def __init__(self, *transforms: ImageTransform):
        self.transforms = transforms

    def __call__(self, img, rng):
        for t in self.transforms:
            img = t(img, rng)
        return img


class NativeImageLoader:
    """Decode an image file / array to float32 **NHWC** numpy.

    Reference: NativeImageLoader(height, width, channels) — asMatrix()
    returns NCHW; here HWC per-image (callers batch-stack to NHWC).
    """

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height, self.width, self.channels = height, width, channels

    def asMatrix(self, path_or_array: Union[str, np.ndarray]) -> np.ndarray:
        from PIL import Image
        if isinstance(path_or_array, np.ndarray):
            img = Image.fromarray(path_or_array.astype(np.uint8))
        else:
            img = Image.open(path_or_array)
        if self.channels == 1:
            img = img.convert("L")
        elif self.channels == 3:
            img = img.convert("RGB")
        elif self.channels == 4:
            img = img.convert("RGBA")
        img = img.resize((self.width, self.height), Image.BILINEAR)
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr


class PathLabelGenerator:
    def getLabelForPath(self, path: str) -> str:
        raise NotImplementedError


class ParentPathLabelGenerator(PathLabelGenerator):
    """Label = name of the file's parent directory (reference:
    ParentPathLabelGenerator — the standard image-folder layout)."""

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(os.path.dirname(path))


class PatternPathLabelGenerator(PathLabelGenerator):
    """Label = split(filename, pattern)[idx] (reference:
    PatternPathLabelGenerator)."""

    def __init__(self, pattern: str, idx: int = 0):
        self.pattern, self.idx = pattern, idx

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(path).split(self.pattern)[self.idx]


class ImageRecordReader(RecordReader):
    """Reads an image directory tree into (image, label_index) records.

    Reference: ImageRecordReader(height, width, channels, labelGenerator).
    ``next()`` yields [HWC float array, int label]; ``loadAll()`` returns
    the batched NHWC feature tensor + int labels — the vectorized path a
    TPU input pipeline actually wants.
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator: Optional[PathLabelGenerator] = None,
                 transform: Optional[ImageTransform] = None,
                 seed: int = 0):
        super().__init__()
        self.loader = NativeImageLoader(height, width, channels)
        self.label_gen = label_generator
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._paths: List[str] = []
        self._labels: List[int] = []
        self._label_names: List[str] = []
        self._i = 0

    def initialize(self, split: Union[InputSplit, str]) -> "ImageRecordReader":
        self._paths = _as_split(split).locations()
        if self.label_gen is not None:
            names = sorted({self.label_gen.getLabelForPath(p)
                            for p in self._paths})
            self._label_names = names
            lut = {n: i for i, n in enumerate(names)}
            self._labels = [lut[self.label_gen.getLabelForPath(p)]
                            for p in self._paths]
        else:
            self._labels = [0] * len(self._paths)
        self._i = 0
        return self

    def getLabels(self) -> List[str]:
        return list(self._label_names)

    def hasNext(self) -> bool:
        return self._i < len(self._paths)

    def next(self) -> List:
        img = self.loader.asMatrix(self._paths[self._i])
        if self.transform is not None:
            img = self.transform(img, self._rng)
        rec = [img, self._labels[self._i]]
        self._i += 1
        return rec

    def reset(self):
        self._i = 0

    def totalRecords(self) -> int:
        return len(self._paths)

    def loadAll(self) -> Tuple[np.ndarray, np.ndarray]:
        """Batched NHWC features + int labels (accelerator handoff)."""
        feats, labels = [], []
        for rec in self:
            feats.append(rec[0])
            labels.append(rec[1])
        return (np.stack(feats).astype(np.float32),
                np.asarray(labels, dtype=np.int32))
