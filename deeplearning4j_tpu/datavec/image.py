"""Image ETL: loader, record reader, label generators, transforms.

Reference: datavec-data-image — NativeImageLoader.java (JavaCV/OpenCV
native decode → CHW float INDArray), ImageRecordReader.java,
ParentPathLabelGenerator.java, transforms under org/datavec/image/
transform/** (ResizeImageTransform, FlipImageTransform, CropImage...).

TPU redesign: decode on host via PIL into **NHWC** numpy (TPU conv
layout; the reference uses NCHW for cuDNN), batch-stack, and hand the
accelerator one contiguous array. Augmentation transforms are
vectorized numpy where possible.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.datavec.records import (FileSplit, InputSplit,
                                                RecordReader, _as_split)




def _to_pil(img: np.ndarray):
    """uint8 PIL image from (H,W,C) incl. single-channel (H,W,1)."""
    from PIL import Image
    a = img.astype(np.uint8)
    if a.ndim == 3 and a.shape[-1] == 1:
        return Image.fromarray(a[..., 0]), True
    return Image.fromarray(a), False


def _from_pil(pil, squeezed: bool) -> np.ndarray:
    arr = np.asarray(pil)
    if squeezed or arr.ndim == 2:
        arr = arr[..., None] if arr.ndim == 2 else arr
    return arr


class ImageTransform:
    """Composable image transform (reference: ImageTransform chain)."""

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, img, rng):
        from PIL import Image
        pil, sq = _to_pil(img)
        return _from_pil(pil.resize((self.w, self.h), Image.BILINEAR),
                         sq)


class FlipImageTransform(ImageTransform):
    """Random horizontal flip with probability p."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng):
        return img[:, ::-1] if rng.random() < self.p else img


class CropImageTransform(ImageTransform):
    """Random crop by up to ``margin`` pixels per side, then pad back."""

    def __init__(self, margin: int):
        self.margin = margin

    def __call__(self, img, rng):
        h, w = img.shape[:2]
        t = int(rng.integers(0, self.margin + 1))
        l = int(rng.integers(0, self.margin + 1))
        b = int(rng.integers(0, self.margin + 1))
        r = int(rng.integers(0, self.margin + 1))
        cropped = img[t:h - b or h, l:w - r or w]
        from PIL import Image
        pil, sq = _to_pil(cropped)
        return _from_pil(pil.resize((w, h), Image.BILINEAR), sq)


class PipelineImageTransform(ImageTransform):
    def __init__(self, *transforms: ImageTransform):
        self.transforms = transforms

    def __call__(self, img, rng):
        for t in self.transforms:
            img = t(img, rng)
        return img


class NativeImageLoader:
    """Decode an image file / array to float32 **NHWC** numpy.

    Reference: NativeImageLoader(height, width, channels) — asMatrix()
    returns NCHW; here HWC per-image (callers batch-stack to NHWC).
    """

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height, self.width, self.channels = height, width, channels

    def asMatrix(self, path_or_array: Union[str, np.ndarray]) -> np.ndarray:
        from PIL import Image
        if isinstance(path_or_array, np.ndarray):
            img = Image.fromarray(path_or_array.astype(np.uint8))
        else:
            img = Image.open(path_or_array)
        if self.channels == 1:
            img = img.convert("L")
        elif self.channels == 3:
            img = img.convert("RGB")
        elif self.channels == 4:
            img = img.convert("RGBA")
        img = img.resize((self.width, self.height), Image.BILINEAR)
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr


class PathLabelGenerator:
    def getLabelForPath(self, path: str) -> str:
        raise NotImplementedError


class ParentPathLabelGenerator(PathLabelGenerator):
    """Label = name of the file's parent directory (reference:
    ParentPathLabelGenerator — the standard image-folder layout)."""

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(os.path.dirname(path))


class PatternPathLabelGenerator(PathLabelGenerator):
    """Label = split(filename, pattern)[idx] (reference:
    PatternPathLabelGenerator)."""

    def __init__(self, pattern: str, idx: int = 0):
        self.pattern, self.idx = pattern, idx

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(path).split(self.pattern)[self.idx]


class ImageRecordReader(RecordReader):
    """Reads an image directory tree into (image, label_index) records.

    Reference: ImageRecordReader(height, width, channels, labelGenerator).
    ``next()`` yields [HWC float array, int label]; ``loadAll()`` returns
    the batched NHWC feature tensor + int labels — the vectorized path a
    TPU input pipeline actually wants.
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator: Optional[PathLabelGenerator] = None,
                 transform: Optional[ImageTransform] = None,
                 seed: int = 0):
        super().__init__()
        self.loader = NativeImageLoader(height, width, channels)
        self.label_gen = label_generator
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._paths: List[str] = []
        self._labels: List[int] = []
        self._label_names: List[str] = []
        self._i = 0

    def initialize(self, split: Union[InputSplit, str]) -> "ImageRecordReader":
        self._paths = _as_split(split).locations()
        if self.label_gen is not None:
            names = sorted({self.label_gen.getLabelForPath(p)
                            for p in self._paths})
            self._label_names = names
            lut = {n: i for i, n in enumerate(names)}
            self._labels = [lut[self.label_gen.getLabelForPath(p)]
                            for p in self._paths]
        else:
            self._labels = [0] * len(self._paths)
        self._i = 0
        return self

    def getLabels(self) -> List[str]:
        return list(self._label_names)

    def hasNext(self) -> bool:
        return self._i < len(self._paths)

    def next(self) -> List:
        img = self.loader.asMatrix(self._paths[self._i])
        if self.transform is not None:
            img = self.transform(img, self._rng)
        rec = [img, self._labels[self._i]]
        self._i += 1
        return rec

    def reset(self):
        self._i = 0

    def totalRecords(self) -> int:
        return len(self._paths)

    def loadAll(self) -> Tuple[np.ndarray, np.ndarray]:
        """Batched NHWC features + int labels (accelerator handoff)."""
        feats, labels = [], []
        for rec in self:
            feats.append(rec[0])
            labels.append(rec[1])
        return (np.stack(feats).astype(np.float32),
                np.asarray(labels, dtype=np.int32))


# ---------------------------------------------------------------------
# round-2 transform breadth (reference: org/datavec/image/transform/**)
# ---------------------------------------------------------------------
class RotateImageTransform(ImageTransform):
    """Random rotation in [-angle, angle] degrees (reference:
    RotateImageTransform; bilinear, edge fill)."""

    def __init__(self, angle: float):
        self.angle = float(angle)

    def __call__(self, img, rng):
        from PIL import Image
        a = float(rng.uniform(-self.angle, self.angle))
        pil, sq = _to_pil(img)
        return _from_pil(pil.rotate(a, resample=Image.BILINEAR), sq)


class ScaleImageTransform(ImageTransform):
    """Random scale by up to ±delta fraction, resized back (reference:
    ScaleImageTransform)."""

    def __init__(self, delta: float = 0.1):
        self.delta = float(delta)

    def __call__(self, img, rng):
        from PIL import Image
        h, w = img.shape[:2]
        s = 1.0 + float(rng.uniform(-self.delta, self.delta))
        nh, nw = max(1, int(h * s)), max(1, int(w * s))
        pil, sq = _to_pil(img)
        scaled = pil.resize((nw, nh), Image.BILINEAR)
        return _from_pil(scaled.resize((w, h), Image.BILINEAR), sq)


class WarpImageTransform(ImageTransform):
    """Random perspective warp: each corner jittered by up to ``delta``
    pixels (reference: WarpImageTransform)."""

    def __init__(self, delta: float):
        self.delta = float(delta)

    def __call__(self, img, rng):
        from PIL import Image
        h, w = img.shape[:2]
        d = self.delta
        # QUAD maps output corners to source points (ul, ll, lr, ur)
        j = lambda: float(rng.uniform(-d, d))
        quad = (j(), j(),
                j(), h + j(),
                w + j(), h + j(),
                w + j(), j())
        pil, sq = _to_pil(img)
        return _from_pil(pil.transform((w, h), Image.QUAD, quad,
                                       Image.BILINEAR), sq)


class ColorConversionTransform(ImageTransform):
    """Color-space conversion (reference: ColorConversionTransform with
    CV codes; here named targets: 'hsv', 'yuv', 'gray')."""

    def __init__(self, target: str = "hsv"):
        if target not in ("hsv", "yuv", "gray"):
            raise ValueError(f"unsupported color target {target!r}")
        self.target = target

    def __call__(self, img, rng):
        if img.shape[-1] != 3:
            if self.target == "gray" and img.shape[-1] == 1:
                return img          # already single-channel
            raise ValueError(
                f"{self.target!r} conversion needs exactly 3 channels; "
                f"got {img.shape[-1]} (drop alpha first)")
        x = img.astype(np.float32) / 255.0
        if self.target == "gray":
            g = (0.2989 * x[..., 0] + 0.587 * x[..., 1]
                 + 0.114 * x[..., 2])
            return (np.repeat(g[..., None], img.shape[-1], -1)
                    * 255.0).astype(img.dtype)
        if self.target == "yuv":
            m = np.array([[0.299, 0.587, 0.114],
                          [-0.14713, -0.28886, 0.436],
                          [0.615, -0.51499, -0.10001]], np.float32)
            yuv = x @ m.T
            yuv[..., 1:] += 0.5
            return (np.clip(yuv, 0, 1) * 255.0).astype(img.dtype)
        # vectorized RGB->HSV (matplotlib-style)
        r, g, b = x[..., 0], x[..., 1], x[..., 2]
        mx, mn = x.max(-1), x.min(-1)
        v = mx
        s = np.where(mx > 0, (mx - mn) / np.maximum(mx, 1e-12), 0.0)
        c = mx - mn
        cs = np.maximum(c, 1e-12)
        hue = np.where(mx == r, ((g - b) / cs) % 6.0,
                       np.where(mx == g, (b - r) / cs + 2.0,
                                (r - g) / cs + 4.0))
        hue = np.where(c == 0, 0.0, hue) / 6.0
        out = np.stack([hue, s, v], -1)
        return (np.clip(out, 0, 1) * 255.0).astype(img.dtype)


class EqualizeHistTransform(ImageTransform):
    """Per-channel histogram equalization (reference:
    EqualizeHistTransform)."""

    def __call__(self, img, rng):
        out = np.empty_like(img)
        u8 = img.astype(np.uint8)
        for c in range(img.shape[-1]):
            ch = u8[..., c]
            hist = np.bincount(ch.reshape(-1), minlength=256)
            cdf = hist.cumsum()
            nz = cdf[cdf > 0]
            if nz.size == 0:
                out[..., c] = ch
                continue
            cdf_min = nz[0]
            denom = max(int(cdf[-1]) - int(cdf_min), 1)
            lut = np.round((cdf - cdf_min) / denom * 255.0)
            out[..., c] = np.clip(lut[ch], 0, 255)
        return out.astype(img.dtype)


class RandomCropTransform(ImageTransform):
    """Crop a random (out_h, out_w) window (reference:
    RandomCropTransform)."""

    def __init__(self, out_h: int, out_w: int):
        self.oh, self.ow = int(out_h), int(out_w)

    def __call__(self, img, rng):
        h, w = img.shape[:2]
        if h < self.oh or w < self.ow:
            raise ValueError(
                f"crop {self.oh}x{self.ow} larger than image {h}x{w}")
        top = int(rng.integers(0, h - self.oh + 1))
        left = int(rng.integers(0, w - self.ow + 1))
        return img[top:top + self.oh, left:left + self.ow]


class BoxImageTransform(ImageTransform):
    """Letterbox into (out_h, out_w): aspect-preserving resize + pad
    (reference: BoxImageTransform)."""

    def __init__(self, out_h: int, out_w: int):
        self.oh, self.ow = int(out_h), int(out_w)

    def __call__(self, img, rng):
        from PIL import Image
        h, w = img.shape[:2]
        scale = min(self.oh / h, self.ow / w)
        nh, nw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
        pil, sq = _to_pil(img)
        resized = _from_pil(pil.resize((nw, nh), Image.BILINEAR), sq)
        out = np.zeros((self.oh, self.ow) + img.shape[2:], img.dtype)
        top = (self.oh - nh) // 2
        left = (self.ow - nw) // 2
        out[top:top + nh, left:left + nw] = resized
        return out


class NoiseImageTransform(ImageTransform):
    """Additive gaussian pixel noise (augmentation; clips to [0,255])."""

    def __init__(self, sigma: float = 8.0):
        self.sigma = float(sigma)

    def __call__(self, img, rng):
        noise = rng.normal(0.0, self.sigma, img.shape)
        return np.clip(img.astype(np.float32) + noise, 0, 255) \
            .astype(img.dtype)


def batch_resize_normalize(images: np.ndarray, height: int, width: int,
                           scale: float = 1.0 / 255.0, mean=None,
                           std=None, n_threads: int = 0) -> np.ndarray:
    """Native-backed batch preprocessing: uint8 NHWC -> float32 NHWC
    resized (half-pixel-centers bilinear) and normalized as
    (x*scale - mean)/std. Multithreaded C++ when the native lib is
    built (native/image_preproc.cpp — the NativeImageLoader/OpenCV hot
    path, ~12x numpy on this host), numpy otherwise. This is the
    vectorized handoff an accelerator input pipeline wants: one
    contiguous array per batch, no per-image Python."""
    from deeplearning4j_tpu import nativeops

    return nativeops.image_resize_normalize(
        images, height, width, scale=scale, mean=mean, std=std,
        n_threads=n_threads)
