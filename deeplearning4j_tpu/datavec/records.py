"""Record readers + input splits.

Reference: org/datavec/api/records/reader/impl/** (CSVRecordReader,
LineRecordReader, CSVSequenceRecordReader, CollectionRecordReader) and
org/datavec/api/split/{FileSplit,NumberedFileInputSplit}.

Readers keep the reference's initialize(split) / hasNext() / next()
surface, but internally parse eagerly into Python lists (host ETL is
not the TPU hot path; vectorization happens in TransformProcess and
RecordReaderDataSetIterator, which batch-convert to numpy).
"""

from __future__ import annotations

import csv
import glob as _glob
import io
import os
import random
from typing import Iterator, List, Optional, Sequence, Union


class InputSplit:
    """Locations of raw input data (reference: org/datavec/api/split)."""

    def locations(self) -> List[str]:
        raise NotImplementedError


class FileSplit(InputSplit):
    """A file, or a directory scanned (recursively) for files with
    allowed extensions; optional shuffle with seed (reference
    FileSplit(File, String[], Random))."""

    def __init__(self, path: str, allowed_extensions: Optional[Sequence[str]] = None,
                 seed: Optional[int] = None):
        self.path = path
        self.allowed = tuple(e.lower().lstrip(".") for e in allowed_extensions) \
            if allowed_extensions else None
        self.seed = seed

    def locations(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        out = []
        for root, _dirs, files in os.walk(self.path):
            for f in sorted(files):
                if self.allowed is None or \
                        f.rsplit(".", 1)[-1].lower() in self.allowed:
                    out.append(os.path.join(root, f))
        out.sort()
        if self.seed is not None:
            random.Random(self.seed).shuffle(out)
        return out


class NumberedFileInputSplit(InputSplit):
    """Pattern like ``/dir/file_%d.txt`` over an inclusive index range
    (reference: NumberedFileInputSplit)."""

    def __init__(self, pattern: str, min_idx: int, max_idx: int):
        if "%d" not in pattern:
            raise ValueError("pattern must contain %d")
        self.pattern = pattern
        self.min_idx = min_idx
        self.max_idx = max_idx

    def locations(self) -> List[str]:
        return [self.pattern % i for i in range(self.min_idx, self.max_idx + 1)]


def _as_split(split: Union[InputSplit, str]) -> InputSplit:
    return FileSplit(split) if isinstance(split, str) else split


class RecordReader:
    """Base reader: initialize(split) then iterate records (lists of
    values). Mirrors the reference interface incl. reset()."""

    def __init__(self):
        self._records: List[List] = []
        self._i = 0

    def initialize(self, split: Union[InputSplit, str]) -> "RecordReader":
        raise NotImplementedError

    def hasNext(self) -> bool:
        return self._i < len(self._records)

    def next(self) -> List:
        r = self._records[self._i]
        self._i += 1
        return r

    def reset(self) -> None:
        self._i = 0

    def totalRecords(self) -> int:
        return len(self._records)

    def allRecords(self) -> List[List]:
        return list(self._records)

    def __iter__(self) -> Iterator[List]:
        self.reset()
        while self.hasNext():
            yield self.next()


def _parse_value(s: str):
    """CSV field → int | float | str (reference keeps Writable subtypes;
    here native types carry the same information)."""
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


class CSVRecordReader(RecordReader):
    """reference: CSVRecordReader(skipNumLines, delimiter).

    Numeric-only files take the native multithreaded parser
    (native/csv_reader.cpp via nativeops — the datavec tokenizer's hot
    path) and all values come back as float; files with any non-numeric
    token fall back to Python csv with int/float/str typing preserved.
    """

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self._records = []
        self._i = 0

    def _try_native(self, loc: str) -> bool:
        from deeplearning4j_tpu import nativeops
        if not nativeops.native_available():
            return False
        try:
            with open(loc, "rb") as f:
                data = f.read()
            if self.skip:
                pos = 0
                for _ in range(self.skip):
                    nxt = data.find(b"\n", pos)
                    if nxt < 0:
                        return False
                    pos = nxt + 1
                data = data[pos:]
            arr = nativeops.csv_parse(data, self.delimiter)
        except ValueError:
            return False
        self._records.extend([list(map(float, row)) for row in arr])
        return True

    def initialize(self, split: Union[InputSplit, str]) -> "CSVRecordReader":
        self._records = []
        for loc in _as_split(split).locations():
            if self._try_native(loc):
                continue
            with open(loc, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))
            for row in rows[self.skip:]:
                if not row:
                    continue
                self._records.append([_parse_value(v.strip()) for v in row])
        self._i = 0
        return self

    def initializeFromString(self, data: str) -> "CSVRecordReader":
        rows = list(csv.reader(io.StringIO(data), delimiter=self.delimiter))
        self._records = [[_parse_value(v.strip()) for v in row]
                         for row in rows[self.skip:] if row]
        self._i = 0
        return self


class LineRecordReader(RecordReader):
    """One record per line, single string value (reference:
    LineRecordReader)."""

    def __init__(self):
        self._records = []
        self._i = 0

    def initialize(self, split: Union[InputSplit, str]) -> "LineRecordReader":
        self._records = []
        for loc in _as_split(split).locations():
            with open(loc) as f:
                for line in f:
                    self._records.append([line.rstrip("\n")])
        self._i = 0
        return self


class CollectionRecordReader(RecordReader):
    """Wrap an in-memory collection of records (reference:
    CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [list(r) for r in records]
        self._i = 0

    def initialize(self, split=None) -> "CollectionRecordReader":
        self._i = 0
        return self


class SequenceRecordReader(RecordReader):
    """Base for readers producing sequences: each record is a list of
    time steps, each time step a list of values."""

    def nextSequence(self) -> List[List]:
        return self.next()


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference: CSVSequenceRecordReader —
    used by the UCI sequence examples)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self._records = []
        self._i = 0

    def initialize(self, split: Union[InputSplit, str]) -> "CSVSequenceRecordReader":
        self._records = []
        for loc in _as_split(split).locations():
            with open(loc, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))
            seq = [[_parse_value(v.strip()) for v in row]
                   for row in rows[self.skip:] if row]
            self._records.append(seq)
        self._i = 0
        return self
