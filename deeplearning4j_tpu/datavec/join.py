"""Join + keyed reduction for tabular records.

Reference: org/datavec/api/transform/join/Join (Inner/LeftOuter/
RightOuter/FullOuter on key columns, executed by LocalTransformExecutor
/ SparkTransformExecutor) and org/datavec/api/transform/reduce/Reducer
(group-by-key aggregation with per-column ReduceOp: SUM, MEAN, MIN,
MAX, COUNT, RANGE, STDEV, FIRST, LAST, COUNT_UNIQUE).
"""

from __future__ import annotations

import math
import statistics
from collections import OrderedDict
from typing import Any, Dict, List, Sequence

from deeplearning4j_tpu.datavec.schema import Schema


class JoinType:
    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"


class Join:
    """Builder mirroring the reference: Join.Builder(type)
    .setJoinColumns(cols).setSchemas(left, right).build(), then
    `execute(left_records, right_records)`."""

    class Builder:
        def __init__(self, join_type: str = JoinType.INNER):
            self.join_type = join_type
            self.join_columns: List[str] = []
            self.left_schema: Schema | None = None
            self.right_schema: Schema | None = None

        def setJoinColumns(self, *cols: str) -> "Join.Builder":
            self.join_columns = list(cols)
            return self

        def setSchemas(self, left: Schema, right: Schema) -> "Join.Builder":
            self.left_schema = left
            self.right_schema = right
            return self

        def build(self) -> "Join":
            valid = (JoinType.INNER, JoinType.LEFT_OUTER,
                     JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)
            if self.join_type not in valid:
                raise ValueError(
                    f"unknown join type {self.join_type!r}; use a "
                    f"JoinType constant: {valid}")
            if not self.join_columns:
                raise ValueError("setJoinColumns() required")
            if self.left_schema is None or self.right_schema is None:
                raise ValueError("setSchemas() required")
            return Join(self.join_type, self.join_columns,
                        self.left_schema, self.right_schema)

    def __init__(self, join_type, join_columns, left, right):
        self.join_type = join_type
        self.join_columns = join_columns
        self.left_schema = left
        self.right_schema = right
        for c in join_columns:
            if not left.hasColumn(c) or not right.hasColumn(c):
                raise ValueError(f"join column '{c}' missing from a side")
        # fail at build time, not when outSchema() happens to be called
        clash = [c for c in right.getColumnNames()
                 if c not in join_columns and left.hasColumn(c)]
        if clash:
            raise ValueError(
                f"non-key columns exist on both sides: {clash}; rename "
                "before joining")

    def outSchema(self) -> Schema:
        """All left columns in their original order (keys stay in
        their left-schema positions), then the right side's non-key
        columns — matching execute()'s row layout."""
        b = Schema.Builder()
        for name in self.left_schema.getColumnNames():
            b.addColumnMeta(self.left_schema.getColumnMeta(name))
        for name in self.right_schema.getColumnNames():
            if name in self.join_columns:
                continue
            b.addColumnMeta(self.right_schema.getColumnMeta(name))
        return b.build()

    def execute(self, left: Sequence[Sequence],
                right: Sequence[Sequence]) -> List[List]:
        lk = [self.left_schema.getIndexOfColumn(c)
              for c in self.join_columns]
        rk = [self.right_schema.getIndexOfColumn(c)
              for c in self.join_columns]
        r_other = [i for i in range(self.right_schema.numColumns())
                   if i not in rk]
        index: "OrderedDict[tuple, List[Sequence]]" = OrderedDict()
        for r in right:
            index.setdefault(tuple(r[i] for i in rk), []).append(r)

        out: List[List] = []
        matched_keys = set()
        for l in left:
            key = tuple(l[i] for i in lk)
            rows = index.get(key)
            if rows:
                matched_keys.add(key)
                for r in rows:
                    out.append(list(l) + [r[i] for i in r_other])
            elif self.join_type in (JoinType.LEFT_OUTER,
                                    JoinType.FULL_OUTER):
                out.append(list(l) + [None] * len(r_other))
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            n_left_other = self.left_schema.numColumns()
            for key, rows in index.items():
                if key in matched_keys:
                    continue
                for r in rows:
                    # key values placed in their left-schema positions
                    row: List[Any] = [None] * n_left_other
                    for c, v in zip(self.join_columns, key):
                        row[self.left_schema.getIndexOfColumn(c)] = v
                    out.append(row + [r[i] for i in r_other])
        return out


class ReduceOp:
    SUM = "SUM"
    MEAN = "MEAN"
    MIN = "MIN"
    MAX = "MAX"
    COUNT = "COUNT"
    RANGE = "RANGE"
    STDEV = "STDEV"
    FIRST = "FIRST"
    LAST = "LAST"
    COUNT_UNIQUE = "COUNT_UNIQUE"


def _reduce(op: str, values: List[Any]):
    if op == ReduceOp.COUNT:
        return len(values)
    if op == ReduceOp.COUNT_UNIQUE:
        return len(set(values))
    if op == ReduceOp.FIRST:
        return values[0] if values else None
    if op == ReduceOp.LAST:
        return values[-1] if values else None
    # skip missing/unparsable cells (None, '', NaN, stray strings) the
    # same way AnalyzeLocal does — CSV-sourced data is dirty by default
    nums = []
    for v in values:
        try:
            f = float(v)
        except (TypeError, ValueError):
            continue
        if not math.isnan(f):
            nums.append(f)
    if not nums:
        return float("nan")
    if op == ReduceOp.SUM:
        return sum(nums)
    if op == ReduceOp.MEAN:
        return sum(nums) / len(nums)
    if op == ReduceOp.MIN:
        return min(nums)
    if op == ReduceOp.MAX:
        return max(nums)
    if op == ReduceOp.RANGE:
        return max(nums) - min(nums)
    if op == ReduceOp.STDEV:
        return statistics.stdev(nums) if len(nums) > 1 else 0.0
    raise ValueError(f"Unknown reduce op: {op}")


class Reducer:
    """Group-by-key aggregation (reference: transform/reduce/Reducer).

    Builder: keyColumns(...), then per-column ops via
    {sum,mean,min,max,count,stdev,first,last,countUnique}Columns(...);
    unspecified columns default to the builder's defaultOp (like the
    reference's Reducer.Builder(default))."""

    class Builder:
        def __init__(self, default_op: str = ReduceOp.FIRST):
            self.default_op = default_op
            self.keys: List[str] = []
            self.ops: Dict[str, str] = {}

        def keyColumns(self, *cols: str) -> "Reducer.Builder":
            self.keys = list(cols)
            return self

        def _set(self, op, cols):
            for c in cols:
                self.ops[c] = op
            return self

        def sumColumns(self, *cols):
            return self._set(ReduceOp.SUM, cols)

        def meanColumns(self, *cols):
            return self._set(ReduceOp.MEAN, cols)

        def minColumns(self, *cols):
            return self._set(ReduceOp.MIN, cols)

        def maxColumns(self, *cols):
            return self._set(ReduceOp.MAX, cols)

        def countColumns(self, *cols):
            return self._set(ReduceOp.COUNT, cols)

        def stdevColumns(self, *cols):
            return self._set(ReduceOp.STDEV, cols)

        def firstColumns(self, *cols):
            return self._set(ReduceOp.FIRST, cols)

        def lastColumns(self, *cols):
            return self._set(ReduceOp.LAST, cols)

        def countUniqueColumns(self, *cols):
            return self._set(ReduceOp.COUNT_UNIQUE, cols)

        def build(self) -> "Reducer":
            if not self.keys:
                raise ValueError("keyColumns() required")
            return Reducer(self.keys, dict(self.ops), self.default_op)

    def __init__(self, keys, ops, default_op):
        self.keys = keys
        self.ops = ops
        self.default_op = default_op

    def _check(self, schema: Schema) -> None:
        # typo'd op columns would silently fall back to the default op
        for c in list(self.keys) + list(self.ops):
            if not schema.hasColumn(c):
                raise ValueError(f"column '{c}' not in schema "
                                 f"{schema.getColumnNames()}")
        bad = [c for c in self.ops if c in self.keys]
        if bad:
            raise ValueError(f"reduce ops target key columns: {bad}")

    def outSchema(self, schema: Schema) -> Schema:
        self._check(schema)
        b = Schema.Builder()
        for name in schema.getColumnNames():
            meta = schema.getColumnMeta(name)
            if name in self.keys:
                b.addColumnMeta(meta)
            else:
                op = self.ops.get(name, self.default_op)
                if op in (ReduceOp.COUNT, ReduceOp.COUNT_UNIQUE):
                    b.addColumnLong(f"{op.lower()}({name})")
                elif op in (ReduceOp.FIRST, ReduceOp.LAST):
                    renamed = b.addColumnMeta(meta)._cols[-1]
                    renamed.name = f"{op.lower()}({name})"
                else:
                    b.addColumnDouble(f"{op.lower()}({name})")
        return b.build()

    def execute(self, schema: Schema,
                records: Sequence[Sequence]) -> List[List]:
        self._check(schema)
        ki = [schema.getIndexOfColumn(c) for c in self.keys]
        groups: "OrderedDict[tuple, List[Sequence]]" = OrderedDict()
        for r in records:
            groups.setdefault(tuple(r[i] for i in ki), []).append(r)
        out = []
        for key, rows in groups.items():
            row: List[Any] = []
            for i, name in enumerate(schema.getColumnNames()):
                if name in self.keys:
                    row.append(key[self.keys.index(name)])
                else:
                    op = self.ops.get(name, self.default_op)
                    row.append(_reduce(op, [r[i] for r in rows]))
            out.append(row)
        return out
