"""Fault-tolerant training: preemption-safe checkpointing, auto-resume,
divergence rollback, and a step watchdog.

Reference mapping (SURVEY.md §5): the reference's recovery story is
CheckpointListener zips + ModelSerializer exact-resume — enough for a
workstation, not for preemptible accelerator fleets where SIGTERM, NaN
storms and flaky host->device links are routine. This module turns the
existing checkpoint substrate (ModelSerializer exact-resume incl.
updater + loss-scale state, ``DataSetIterator.get_state/set_state``)
into an actual fault-tolerance layer, one policy object wired through
all three fit loops (MultiLayerNetwork, ComputationGraph,
ShardedTrainer):

- **Preemption safety** — SIGTERM/SIGINT set a flag; at the next step
  boundary the loop writes ONE atomic resumable bundle (model + updater
  + loss-scale + epoch/iteration counters + RNG key + data-iterator
  position) and returns cleanly. A second signal aborts immediately.
- **Auto-resume** — ``fit(..., auto_resume=dir)`` discovers the newest
  bundle whose manifest digests verify, falls back to the previous one
  on corruption, restores everything, and continues mid-epoch on the
  NEXT batch (iterator position travels in the bundle). Bundles are
  retired when the run completes, so a finished job never re-resumes
  stale state.
- **Divergence guard** — a rolling window of recent losses; NaN/Inf or
  a spike past ``spike_factor`` x the window median rolls the model
  back to a periodic in-memory device snapshot and SKIPS the offending
  batch, up to ``max_rollbacks`` before raising ``DivergenceError``.
  (Reading the loss forces one device sync per step — the price of the
  guard; set ``divergence_window=0`` to disable.)
- **Step watchdog** — a step exceeding ``step_deadline`` seconds dumps
  every thread's stack plus a telemetry snapshot to the log (the data
  needed to diagnose a wedged collective or a stuck transfer), without
  killing the run.
- **Transfer retry** — the policy configures the wrapping
  ``DevicePrefetchIterator`` (if one feeds the loop) with exponential-
  backoff retries and poison-batch quarantine (see
  datasets/device_prefetch.py).

Identity guarantee: with no FaultTolerance (``fit`` called without
``fault_tolerance``/``auto_resume``), the fit loops run their original
code paths bit-for-bit — this module is never imported.

Every recovery action lands in the telemetry registry
(``dl4j_tpu_ft_*``, ``dl4j_tpu_transfer_*``, ``dl4j_tpu_watchdog_*``
counters — docs/OBSERVABILITY.md), and all of it is exercised by the
fault-injection harness in profiler/chaos.py.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import logging
import os
import random
import re
import shutil
import signal
import statistics
import sys
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.profiler import chaos as _chaos
from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")

_BUNDLE_RE = re.compile(r"bundle-(\d+)(?:-\d+)?$")
_RESUME_FORMAT = "deeplearning4j_tpu-ft-1"


class DivergenceError(RuntimeError):
    """Raised when the divergence guard exhausts its rollback budget —
    the run is not recovering, a human needs to look."""


# ======================================================================
# resumable checkpoint bundles
# ======================================================================
def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _mesh_topology(trainer) -> Optional[Dict[str, Any]]:
    """Mesh topology record for the bundle manifest: replica counts +
    process count + update-sharding mode. Restore compares it against
    the restoring trainer's mesh so a topology change is LOGGED (the
    re-shard itself is automatic: zero-mode state rebuilds from the
    canonical trees, which are replica-count-free)."""
    if trainer is None or getattr(trainer, "mesh", None) is None:
        return None
    import jax

    return {
        "data": int(trainer.mesh.shape.get("data", 1)),
        "model": int(trainer.mesh.shape.get("model", 1)),
        "processes": int(jax.process_count()),
        "mode": trainer.mode,
        "update_sharding": getattr(trainer, "update_sharding", None),
    }


def _write_zero_shards(tmp: str, trainer) -> Optional[str]:
    """Zero mode: each host additionally writes ITS addressable master/
    opt flat shards (``zero_shards_p<process>.npz``) — checkpoint
    bandwidth scales with hosts, no host materializes state it does
    not own. The canonical model.zip stays the topology-free restore
    source; the shard file carries the exact device-level layout for
    same-topology forensics/restore."""
    z = getattr(trainer, "_zero", None)
    layout = getattr(trainer, "_zero_layout", None)
    if z is None or layout is None:
        return None
    import jax

    member = f"zero_shards_p{jax.process_index()}.npz"
    shards = layout.addressable_shards(z["masters"], z["opt"])
    path = os.path.join(tmp, member)
    with open(path, "wb") as f:
        np.savez(f, **shards)
        f.flush()
        os.fsync(f.fileno())
    return member


def _host_identity() -> Tuple[int, int]:
    """(process_index, process_count) of this host — 0/1 when jax (or
    its distributed runtime) is not up, so pure-host bundle tooling
    never forces a backend."""
    try:
        import jax

        return int(jax.process_index()), int(jax.process_count())
    except Exception:
        return 0, 1


def write_bundle(directory: str, model, resume_meta: Dict[str, Any],
                 keep_last: int = 2, trainer=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 host: Optional[str] = None) -> str:
    """Write one atomic resumable bundle under ``directory`` and prune
    to the newest ``keep_last``. Layout::

        bundle-<iteration>/
            model.zip      ModelSerializer archive (params + updater +
                           loss-scale + iteration/epoch)
            resume.json    RNG key, iterator position, epochs remaining
            manifest.json  sha256 digests of the members + the mesh
                           topology the bundle was saved under
            zero_shards_p<i>.npz   (update-sharded trainers only) this
                           host's addressable master/opt flat shards

    Atomicity: everything is written into a writer-unique temp
    directory, each file fsynced, then the directory is renamed into
    place and the parent fsynced — a crash mid-save leaves only a temp
    dir that discovery ignores, never a half bundle under a valid name.
    ``keep_last >= 2`` is what makes digest-verified fallback possible:
    if the newest bundle is torn, the previous one still restores."""
    from deeplearning4j_tpu.util.model_serializer import (
        ModelSerializer, fsync_directory,
    )

    os.makedirs(directory, exist_ok=True)
    iteration = int(model.getIterationCount())
    name = f"bundle-{iteration:010d}"
    final = os.path.join(directory, name)
    n = 0
    while os.path.exists(final):   # re-preemption at the same step
        n += 1
        final = os.path.join(directory, f"{name}-{n}")
    tmp = os.path.join(directory,
                       f".{name}.{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp")
    os.makedirs(tmp)

    def _write_member(member: str, obj) -> None:
        # plain write + fsync: the tmp dir is unpublished (discovery
        # ignores dot-dirs), so the single publish point is the
        # directory rename below — per-member rename dances would buy
        # no extra crash-safety, just fsync cycles spent inside the
        # SIGTERM grace period
        with open(os.path.join(tmp, member), "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())

    if process_index is None or process_count is None:
        pidx, pcnt = _host_identity()
        process_index = pidx if process_index is None else process_index
        process_count = pcnt if process_count is None else process_count
    try:
        # writeModel is itself atomic (temp + fsync + replace) inside tmp
        ModelSerializer.writeModel(model, os.path.join(tmp, "model.zip"))
        _write_member("resume.json", dict(resume_meta,
                                          format=_RESUME_FORMAT))
        members = ["model.zip", "resume.json"]
        zmember = _write_zero_shards(tmp, trainer)
        if zmember is not None:
            members.append(zmember)
        manifest = {
            "format": _RESUME_FORMAT,
            "iteration": iteration,
            "mesh": _mesh_topology(trainer),
            "host": host if host is not None
            else f"p{process_index}",
            "digests": {m: _sha256(os.path.join(tmp, m))
                        for m in members},
        }
        shared_protocol = zmember is not None and process_count > 1
        if shared_protocol:
            # shared-filesystem contract: every host owns one shard
            # member, and a bundle is COMPLETE only when all of them
            # have been published (foreign shards carry .sha256
            # sidecars — see publish_foreign_shard / _bundle_complete)
            manifest["expected_shards"] = [
                f"zero_shards_p{i}.npz" for i in range(process_count)]
        _write_member("manifest.json", manifest)
        fsync_directory(tmp)
        os.replace(tmp, final)
        fsync_directory(directory)
    finally:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
    # the process-0-only pruning rule exists for the SHARED multi-host
    # shard protocol (a peer's still-publishing shard must not be
    # pruned out from under it); hosts writing independent full
    # bundles (no expected_shards) keep the historical per-host
    # keep_last enforcement — their directories may be private disks
    _prune_bundles(directory, keep_last,
                   process_index=process_index if shared_protocol
                   else 0)
    return final


def _list_bundles(directory: str) -> List[Tuple[int, str]]:
    """(iteration, path) for every bundle dir, newest first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for nm in names:
        m = _BUNDLE_RE.fullmatch(nm)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, nm)))
    # name (with its -k re-preemption suffix) breaks iteration ties in
    # creation order
    return sorted(out, key=lambda t: (t[0], t[1]), reverse=True)


def _bundle_complete(path: str) -> bool:
    """Cheap multi-host completeness probe (NO digest pass): the
    manifest parses and every expected per-host shard member is
    present with its integrity record (manifest digest for the
    writing host, ``.sha256`` sidecar for foreign hosts). Single-host
    bundles have no ``expected_shards`` and are complete iff the
    manifest parses."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("format") != _RESUME_FORMAT:
            return False
        digests = manifest.get("digests", {})
        for member in manifest.get("expected_shards", []):
            if not os.path.exists(os.path.join(path, member)):
                return False
            if member not in digests and not os.path.exists(
                    os.path.join(path, member + ".sha256")):
                return False
        return True
    except (OSError, ValueError):
        return False


def _prune_bundles(directory: str, keep_last: int,
                   process_index: Optional[int] = None) -> None:
    """keep_last enforcement, multi-host safe: ONLY process 0 prunes
    (each host pruning independently is a race against a slower host
    still publishing its shard), keep_last counts only COMPLETE
    bundles (every expected per-host shard present — see
    ``_bundle_complete``), and an incomplete bundle at or newer than
    the pruning cutoff is never deleted: it is a slower host's
    still-being-written checkpoint, not garbage. Incomplete bundles
    OLDER than the cutoff are torn leftovers and go."""
    if process_index is None:
        process_index = _host_identity()[0]
    if process_index != 0:
        return
    bundles = _list_bundles(directory)
    complete = [(it, p) for it, p in bundles if _bundle_complete(p)]
    if not complete:
        return
    kept = complete[:max(keep_last, 1)]
    keep = {p for _, p in kept}
    cutoff = kept[-1][0]        # iteration of the oldest kept bundle
    for it, path in bundles:
        if path in keep:
            continue
        if it >= cutoff and not _bundle_complete(path):
            continue            # a slow host may still be publishing
        shutil.rmtree(path, ignore_errors=True)


def _await_bundle_for_iteration(directory: str, iteration: int,
                                member: str,
                                timeout_s: float) -> str:
    """The bundle dir a NON-zero host must attach its shard to: the
    newest dir process 0 published for ``iteration`` that does not
    yet hold ``member``. Resolved by LISTING, never by recomputing
    the name — a re-preemption at the same step makes process 0
    publish a ``-k``-suffixed dir, and writing the shard into the
    unsuffixed older one would corrupt a bundle that already
    validated."""
    deadline = time.monotonic() + timeout_s
    while True:
        cands = [p for it, p in _list_bundles(directory)
                 if it == iteration]
        # _list_bundles sorts suffixed (newer) dirs first at equal
        # iteration; prefer the newest one still missing our shard
        for p in cands:
            if not os.path.exists(os.path.join(p, member)):
                return p
        if cands:
            return cands[0]
        if time.monotonic() > deadline:
            raise OSError(
                f"no bundle for iteration {iteration} was published "
                f"by process 0 within {timeout_s}s — cannot attach "
                f"shard {member}")
        time.sleep(0.05)


def publish_foreign_shard(directory: str, iteration: int, member: str,
                          data: Dict[str, np.ndarray],
                          timeout_s: float = 10.0) -> str:
    """Shared-filesystem shard publish for a NON-zero host: wait for
    process 0 to rename the bundle directory into place, then publish
    this host's ``zero_shards_p<i>.npz`` next to it atomically
    (unique tmp + fsync + replace) with a ``.sha256`` sidecar so any
    survivor can digest-verify it without this host."""
    bundle_path = _await_bundle_for_iteration(directory, iteration,
                                              member, timeout_s)
    from deeplearning4j_tpu.util.model_serializer import (
        fsync_directory, unique_tmp_path,
    )

    final = os.path.join(bundle_path, member)
    tmp = unique_tmp_path(final)
    with open(tmp, "wb") as f:
        np.savez(f, **data)
        f.flush()
        os.fsync(f.fileno())
    digest = _sha256(tmp)
    with open(tmp + ".sha", "w") as f:
        f.write(digest)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp + ".sha", final + ".sha256")
    os.replace(tmp, final)
    fsync_directory(bundle_path)
    return final


def validate_bundle(path: str, raise_io: bool = False) -> bool:
    """True iff the manifest parses and every member's sha256 matches —
    the corruption detector behind newest-valid discovery. Foreign
    per-host shards (``expected_shards`` beyond this host's manifest
    digests) verify against their ``.sha256`` sidecars. With
    ``raise_io`` an OSError propagates instead of reading as
    corruption — the shared-filesystem retry loop's hook (a transient
    NFS hiccup must not condemn a good bundle)."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("format") != _RESUME_FORMAT:
            return False
        for member, digest in manifest["digests"].items():
            if _sha256(os.path.join(path, member)) != digest:
                return False
        for member in manifest.get("expected_shards", []):
            if member in manifest["digests"]:
                continue
            with open(os.path.join(path, member + ".sha256")) as f:
                if _sha256(os.path.join(path, member)) != f.read().strip():
                    return False
        with open(os.path.join(path, "resume.json")) as f:
            json.load(f)
        return True
    except OSError:
        if raise_io:
            raise
        return False
    except (ValueError, KeyError):
        return False


def latest_valid_bundle(directory: str) -> Optional[str]:
    """Newest bundle whose digests verify; corrupt ones are skipped
    with a loud warning (torn by a crash mid-save, truncated by a full
    disk...) so the fallback is visible, not silent."""
    for _, path in _list_bundles(directory):
        if validate_bundle(path):
            return path
        log.warning("resilience: bundle %s failed digest validation — "
                    "falling back to the previous one", path)
    return None


def retire_bundles(directory: str) -> None:
    """Remove all bundles — called when a run COMPLETES, so a later fit
    with auto_resume on the same dir starts fresh instead of reviving
    the finished run's final state."""
    for _, path in _list_bundles(directory):
        shutil.rmtree(path, ignore_errors=True)


# ======================================================================
# bundle stores
# ======================================================================
class BundleStore:
    """Where resumable bundles live and how survivors discover them.

    The base class is the PR 4 story: one local directory, this
    process the only writer, discovery = newest digest-valid dir. The
    control plane's phase-2 migration needs more: when a WORKER HOST
    dies, its local disk dies with it, so the surviving host that
    inherits the job must find the bundle somewhere it can reach —
    that is ``SharedFSBundleStore``. ``FaultTolerance`` accepts either
    (``bundle_store=``); ``checkpoint_dir=`` keeps meaning a plain
    local store.

    ``io_retries``/``io_backoff``: transient-I/O posture. Local disks
    failing is fatal (0 retries keeps the historical fail-fast);
    shared filesystems hiccup routinely, so the shared store retries
    ``OSError`` with exponential backoff + jitter before declaring a
    bundle invalid or falling back to the previous one
    (``dl4j_tpu_ft_bundle_io_retries_total`` counts, mirroring the
    PR 4 transfer-retry policy)."""

    kind = "local"

    def __init__(self, directory, *, io_retries: int = 0,
                 io_backoff: float = 0.05):
        self.directory = os.fspath(directory)
        self.io_retries = int(io_retries)
        self.io_backoff = float(io_backoff)

    # ------------------------------------------------------------ retry
    def _retrying(self, what: str, fn: Callable, *a, **kw):
        attempt = 0
        while True:
            try:
                return fn(*a, **kw)
            except OSError as e:
                if attempt >= self.io_retries:
                    raise
                attempt += 1
                delay = self.io_backoff * (2 ** (attempt - 1)) \
                    * (1.0 + random.random())
                if _telemetry.enabled():
                    _telemetry.MetricsRegistry.get_default().counter(
                        _telemetry.FT_BUNDLE_IO_RETRIES,
                        "transient bundle-store I/O failures retried "
                        "with backoff").inc(op=what)
                log.warning(
                    "resilience: transient bundle-store I/O failure "
                    "during %s (%s: %s) — retry %d/%d in %.2fs",
                    what, type(e).__name__, e, attempt,
                    self.io_retries, delay)
                time.sleep(delay)

    # -------------------------------------------------------------- api
    def write(self, model, resume_meta: Dict[str, Any],
              keep_last: int = 2, trainer=None) -> str:
        return self._retrying(
            "write_bundle", write_bundle, self.directory, model,
            resume_meta, keep_last=keep_last, trainer=trainer)

    def _validate_once(self, path: str) -> bool:
        try:
            return validate_bundle(path, raise_io=True)
        except FileNotFoundError:
            # an ABSENT member is incompleteness (a slower host still
            # publishing, or a torn bundle) — retrying the read won't
            # make it appear; only EIO/ESTALE-class errors are the
            # transient filesystem hiccups the backoff exists for
            return False

    def validate(self, path: str) -> bool:
        try:
            return self._retrying("validate_bundle",
                                  self._validate_once, path)
        except OSError:
            # the retry budget is spent: NOW it reads as corruption and
            # discovery falls back to the previous bundle
            return False

    def latest_valid(self) -> Optional[str]:
        try:
            bundles = self._retrying("list_bundles", _list_bundles,
                                     self.directory)
        except OSError:
            return None
        for _, path in bundles:
            if self.validate(path):
                return path
            log.warning("resilience: bundle %s failed digest "
                        "validation — falling back to the previous "
                        "one", path)
        return None

    def discover(self) -> List[Dict[str, Any]]:
        """Every bundle the store can see, newest first — including
        who wrote it and whether it is complete/valid. The cross-host
        survivor's view: after a worker host dies, any other host
        enumerates the dead host's checkpoints here."""
        out = []
        for it, path in _list_bundles(self.directory):
            host = None
            try:
                with open(os.path.join(path, "manifest.json")) as f:
                    host = json.load(f).get("host")
            except (OSError, ValueError):
                pass
            out.append({"iteration": it, "path": path, "host": host,
                        "complete": _bundle_complete(path),
                        "valid": self.validate(path)})
        return out

    def retire(self) -> None:
        retire_bundles(self.directory)

    def describe(self) -> str:
        return f"{self.kind}:{self.directory}"


class LocalBundleStore(BundleStore):
    """Single-host local-directory store — the explicit spelling of
    ``FaultTolerance(checkpoint_dir=...)``."""


class SharedFSBundleStore(BundleStore):
    """Bundle store on a shared/remote filesystem (NFS, Lustre, a
    FUSE-mounted object bucket): one namespace directory that EVERY
    worker host mounts, so a bundle written by a host that later died
    restores on any survivor.

    Multi-host writes: process 0 publishes the canonical bundle
    (model.zip + resume.json + manifest listing every expected
    per-host shard); other processes attach their
    ``zero_shards_p<i>.npz`` via ``publish_foreign_shard`` (atomic,
    sidecar-digested). Only process 0 prunes, and only around
    COMPLETE bundles — see ``_prune_bundles`` for the race this
    closes. Transient ``OSError`` retries with backoff + jitter are on
    by default (``io_retries=4``)."""

    kind = "shared_fs"

    def __init__(self, root, namespace: str = "default", *,
                 io_retries: int = 4, io_backoff: float = 0.05,
                 publish_wait_s: float = 10.0,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        super().__init__(os.path.join(os.fspath(root), namespace),
                         io_retries=io_retries, io_backoff=io_backoff)
        self.namespace = str(namespace)
        self.publish_wait_s = float(publish_wait_s)
        # injectable identity: tests (and supervisors spawning workers
        # that are not jax processes) pin these explicitly
        self._process_index = process_index
        self._process_count = process_count

    def _identity(self) -> Tuple[int, int]:
        if self._process_index is not None:
            return self._process_index, self._process_count or 1
        return _host_identity()

    def write(self, model, resume_meta: Dict[str, Any],
              keep_last: int = 2, trainer=None) -> str:
        pidx, pcnt = self._identity()
        if pidx == 0:
            return self._retrying(
                "write_bundle", write_bundle, self.directory, model,
                resume_meta, keep_last=keep_last, trainer=trainer,
                process_index=pidx, process_count=pcnt)
        # non-zero host: publish only this host's shard into the
        # bundle process 0 names (iteration is globally agreed — every
        # host sits at the same step boundary when a checkpoint fires)
        iteration = int(model.getIterationCount())
        z = getattr(trainer, "_zero", None)
        layout = getattr(trainer, "_zero_layout", None)
        if z is None or layout is None:
            # nothing host-local to contribute
            return os.path.join(self.directory,
                                f"bundle-{iteration:010d}")
        shards = layout.addressable_shards(z["masters"], z["opt"])
        return self._retrying(
            "publish_foreign_shard", publish_foreign_shard,
            self.directory, iteration,
            f"zero_shards_p{pidx}.npz", shards,
            timeout_s=self.publish_wait_s)


# ======================================================================
# object-store bundle store (rename-less commit protocol)
# ======================================================================
class InMemoryObjectStore:
    """Dict-backed object-store client — the in-process test double for
    the ``put/get/list/delete`` protocol ``ObjectStoreBundleStore``
    speaks. A missing key raises ``KeyError`` (deterministic absence),
    never ``OSError`` (transient trouble) — the retry loop must not
    burn its budget waiting for an object that does not exist."""

    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key, data) -> None:
        with self._lock:
            self._blobs[str(key)] = bytes(data)

    def get(self, key) -> bytes:
        with self._lock:
            try:
                return self._blobs[str(key)]
            except KeyError:
                raise KeyError(f"no object at {key}") from None

    def list(self, prefix) -> List[str]:
        p = str(prefix)
        with self._lock:
            return sorted(k for k in self._blobs if k.startswith(p))

    def delete(self, key) -> None:
        with self._lock:
            self._blobs.pop(str(key), None)

    def describe(self) -> str:
        return f"memory({len(self._blobs)} objects)"


class LocalObjectStore:
    """Filesystem-backed object-store client: ``/``-separated keys map
    to files under ``root``. ``put`` is DELIBERATELY a plain
    open/write — no tmp-rename, no fsync — because the class emulates
    bucket semantics, where atomicity comes from the COMMIT PROTOCOL
    above it, not from the storage layer (and where a torn upload
    really does leave a truncated blob under the key). Two instances
    over one root are two hosts sharing a bucket — the cross-host
    discovery substrate for tests and single-machine drills."""

    def __init__(self, root):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key) -> str:
        return os.path.join(self.root, *str(key).split("/"))

    def put(self, key, data) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(bytes(data))

    def get(self, key) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(f"no object at {key}") from None

    def list(self, prefix) -> List[str]:
        p = str(prefix)
        out = []
        for dirpath, _, files in os.walk(self.root):
            for nm in files:
                rel = os.path.relpath(os.path.join(dirpath, nm),
                                      self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(p):
                    out.append(key)
        return sorted(out)

    def delete(self, key) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def describe(self) -> str:
        return f"file({self.root})"


class ObjectStoreBundleStore(BundleStore):
    """Bundle store over S3/GCS-style object storage — no rename, no
    fsync, no atomic directory publish to lean on, so the atomicity
    the local stores get from ``os.replace`` is rebuilt as a COMMIT
    PROTOCOL:

    - every write attempt uploads its members under a fresh
      write-unique prefix ``<ns>/bundles/<name>/<token>/<member>``;
    - the COMMIT OBJECT ``<ns>/commit/<name>`` — the manifest plus
      the winning token and per-member digests — is written LAST.
      Readers enumerate ONLY the commit namespace, so an uncommitted
      (crashed, torn, still-uploading) prefix is invisible by
      construction;
    - non-zero hosts attach ``zero_shards_p<i>.npz`` under
      ``<ns>/shards/<name>/`` with a ``.sha256`` marker object
      uploaded AFTER the blob — no marker, no shard, exactly the
      sidecar contract of ``publish_foreign_shard``;
    - every download digest-verifies against the commit/marker before
      use: a torn upload (half a blob under the right key — chaos's
      ``store_torn``) is detected and the reader falls back to the
      previous commit, mirroring ``latest_valid_bundle``.

    Restore needs local files (``_restore_bundle`` reads paths), so
    ``latest_valid``/``discover`` MATERIALIZE commits into the local
    cache directory, which doubles as the ``FaultTolerance``
    ``checkpoint_dir`` anchor and as the offline fallback when the
    store is unreachable. ``client`` is anything speaking
    put/get/list/delete (``InMemoryObjectStore``,
    ``LocalObjectStore``, a real SDK adapter); it is automatically
    wrapped by ``chaos.FaultyObjectStore.from_env`` so the
    ``DL4J_TPU_CHAOS_STORE_*`` knobs inject faults without code
    changes. Transient ``OSError`` retries with backoff are on by
    default (``io_retries=4``), counted in
    ``dl4j_tpu_ft_bundle_io_retries_total``."""

    kind = "object_store"

    def __init__(self, client, namespace: str = "default", *,
                 cache_dir=None, io_retries: int = 4,
                 io_backoff: float = 0.05,
                 publish_wait_s: float = 10.0,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        if cache_dir is None:
            cache_dir = tempfile.mkdtemp(
                prefix="dl4j_tpu_ostore_cache.")
        super().__init__(cache_dir, io_retries=io_retries,
                         io_backoff=io_backoff)
        self.client = _chaos.FaultyObjectStore.from_env(client)
        self.namespace = str(namespace)
        self.publish_wait_s = float(publish_wait_s)
        self._process_index = process_index
        self._process_count = process_count

    def _identity(self) -> Tuple[int, int]:
        if self._process_index is not None:
            return self._process_index, self._process_count or 1
        return _host_identity()

    def _key(self, *parts: str) -> str:
        return "/".join((self.namespace,) + parts)

    # ------------------------------------------------------------ write
    def write(self, model, resume_meta: Dict[str, Any],
              keep_last: int = 2, trainer=None) -> str:
        pidx, pcnt = self._identity()
        if pidx != 0:
            return self._write_shard(model, trainer)
        # stage locally first: the cache gets a normal atomic bundle
        # (and local keep_last pruning) for free, and a crash between
        # here and the commit upload still leaves a restorable local
        # checkpoint for a same-host restart
        path = self._retrying(
            "write_bundle", write_bundle, self.directory, model,
            resume_meta, keep_last=keep_last, trainer=trainer,
            process_index=0, process_count=pcnt)
        name = os.path.basename(path)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        token = uuid.uuid4().hex
        members: Dict[str, Any] = {}
        for member, digest in manifest["digests"].items():
            with open(os.path.join(path, member), "rb") as f:
                data = f.read()
            self._retrying(
                "put", self.client.put,
                self._key("bundles", name, token, member), data)
            members[member] = {"sha256": digest, "size": len(data)}
        commit = dict(manifest, prefix=token, members=members)
        self._retrying(
            "commit", self.client.put, self._key("commit", name),
            json.dumps(commit).encode())
        try:
            self._prune_remote(keep_last)
        except OSError as e:
            # hygiene, not correctness: uncommitted garbage is already
            # invisible; stale commits just cost bucket space
            log.warning("resilience: remote bundle pruning failed "
                        "(%s) — will retry at the next checkpoint", e)
        return path

    def _write_shard(self, model, trainer) -> str:
        """Non-zero host: attach this host's shard blob + digest
        marker to the bundle process 0 committed for this step."""
        iteration = int(model.getIterationCount())
        pidx, _ = self._identity()
        member = f"zero_shards_p{pidx}.npz"
        z = getattr(trainer, "_zero", None)
        layout = getattr(trainer, "_zero_layout", None)
        if z is None or layout is None:
            return self._key("commit", f"bundle-{iteration:010d}")
        shards = layout.addressable_shards(z["masters"], z["opt"])
        buf = io.BytesIO()
        np.savez(buf, **shards)
        data = buf.getvalue()
        name = self._await_commit(iteration)
        blob_key = self._key("shards", name, member)
        self._retrying("put", self.client.put, blob_key, data)
        # marker LAST: its presence certifies the blob fully uploaded
        self._retrying(
            "put", self.client.put, blob_key + ".sha256",
            hashlib.sha256(data).hexdigest().encode())
        return blob_key

    def _await_commit(self, iteration: int) -> str:
        deadline = time.monotonic() + self.publish_wait_s
        while True:
            try:
                for it, name, _ in self._commits():
                    if it == iteration:
                        return name
            except OSError:
                pass            # keep polling until the deadline
            if time.monotonic() > deadline:
                raise OSError(
                    f"no commit for iteration {iteration} was "
                    f"published by process 0 within "
                    f"{self.publish_wait_s}s — cannot attach shard")
            time.sleep(0.05)

    # ------------------------------------------------------- discovery
    def _commits(self) -> List[Tuple[int, str, Dict[str, Any]]]:
        """(iteration, name, commit manifest) for every committed
        bundle, newest first — the ONLY enumeration readers do."""
        out = []
        prefix = self._key("commit") + "/"
        for key in self._retrying("list_commits", self.client.list,
                                  prefix):
            name = key[len(prefix):] if key.startswith(prefix) \
                else key.rsplit("/", 1)[-1]
            m = _BUNDLE_RE.fullmatch(name)
            if not m:
                continue
            try:
                manifest = json.loads(self._retrying(
                    "get_commit", self.client.get, key))
            except (KeyError, ValueError) as e:
                log.warning("resilience: unreadable commit object %s "
                            "(%s) — skipping", key, e)
                continue
            if manifest.get("format") != _RESUME_FORMAT:
                continue
            out.append((int(m.group(1)), name, manifest))
        return sorted(out, key=lambda t: (t[0], t[1]), reverse=True)

    def _materialize(self, name: str,
                     manifest: Dict[str, Any]) -> Optional[str]:
        """Download a committed bundle into the local cache,
        digest-verifying every member against the commit. Returns the
        local path, or None when the bundle is incomplete (a shard
        marker missing) or any object fails verification (torn
        upload) — the caller falls back to the previous commit."""
        token = manifest.get("prefix", "")
        members = manifest.get("members", {})
        digests = manifest.get("digests", {})
        plan = [(m, self._key("bundles", name, token, m),
                 info["sha256"]) for m, info in members.items()]
        foreign = []
        for member in manifest.get("expected_shards", []):
            if member in members or member in digests:
                continue
            marker = self._key("shards", name, member) + ".sha256"
            try:
                want = self._retrying(
                    "get", self.client.get, marker).decode().strip()
            except KeyError:
                log.warning("resilience: bundle %s is incomplete — "
                            "shard marker %s not yet published",
                            name, member)
                return None
            foreign.append((member, self._key("shards", name, member),
                            want))
        local = os.path.join(self.directory, name)
        os.makedirs(local, exist_ok=True)
        for member, key, want in plan + foreign:
            dst = os.path.join(local, member)
            if os.path.exists(dst) and _sha256(dst) == want:
                continue        # warm cache: already verified local
            try:
                data = self._retrying("get", self.client.get, key)
            except KeyError:
                log.warning("resilience: bundle %s is missing object "
                            "%s — treating as incomplete", name, key)
                return None
            if hashlib.sha256(data).hexdigest() != want:
                log.warning("resilience: object %s failed digest "
                            "validation (torn upload?) — falling "
                            "back to the previous bundle", key)
                return None
            tmp = dst + f".{uuid.uuid4().hex[:8]}.tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, dst)
        for member, _, want in foreign:
            side = os.path.join(local, member + ".sha256")
            if not os.path.exists(side):
                with open(side, "w") as f:
                    f.write(want)
        # a local manifest makes the materialized dir indistinguishable
        # from a write_bundle dir: base validate() and _restore_bundle
        # work on it unchanged
        local_manifest = {k: v for k, v in manifest.items()
                          if k not in ("prefix", "members")}
        local_manifest["digests"] = dict(
            digests, **{m: info["sha256"]
                        for m, info in members.items()})
        with open(os.path.join(local, "manifest.json"), "w") as f:
            json.dump(local_manifest, f)
        return local

    def latest_valid(self) -> Optional[str]:
        try:
            commits = self._commits()
        except OSError as e:
            log.warning("resilience: object store unreachable (%s) — "
                        "falling back to the local cache", e)
            return super().latest_valid()
        for _, name, manifest in commits:
            try:
                path = self._materialize(name, manifest)
            except OSError as e:
                log.warning("resilience: object store unreachable "
                            "mid-download (%s) — falling back to the "
                            "local cache", e)
                return super().latest_valid()
            if path is not None and self.validate(path):
                return path
            log.warning("resilience: committed bundle %s did not "
                        "materialize/validate — falling back to the "
                        "previous one", name)
        # a REACHABLE store with no valid commit is authoritative: a
        # staged-but-never-committed local bundle "didn't happen"
        # cluster-wide, and after retire() nothing may resume
        return None

    def discover(self) -> List[Dict[str, Any]]:
        try:
            commits = self._commits()
        except OSError:
            return super().discover()
        out = []
        for it, name, manifest in commits:
            path = self._materialize(name, manifest)
            out.append({
                "iteration": it,
                "path": path if path else self._key("commit", name),
                "host": manifest.get("host"),
                "complete": self._remote_complete(name, manifest),
                "valid": path is not None and self.validate(path),
            })
        return out

    def _remote_complete(self, name: str,
                         manifest: Dict[str, Any]) -> bool:
        """Cheap completeness probe, bucket edition: every expected
        shard is either a committed member or has its marker object
        (no digest pass — mirrors ``_bundle_complete``)."""
        members = manifest.get("members", {})
        digests = manifest.get("digests", {})
        for member in manifest.get("expected_shards", []):
            if member in members or member in digests:
                continue
            try:
                self._retrying(
                    "get", self.client.get,
                    self._key("shards", name, member) + ".sha256")
            except KeyError:
                return False
        return True

    # ------------------------------------------------------- retention
    def _prune_remote(self, keep_last: int) -> None:
        """keep_last in the bucket, same rules as ``_prune_bundles``:
        process 0 only, count only COMPLETE bundles, never delete an
        incomplete bundle at/after the cutoff (a slower host is still
        uploading its shard)."""
        if self._identity()[0] != 0:
            return
        commits = self._commits()
        complete = [(it, nm, mf) for it, nm, mf in commits
                    if self._remote_complete(nm, mf)]
        if not complete:
            return
        kept = complete[:max(keep_last, 1)]
        keep = {nm for _, nm, _ in kept}
        cutoff = kept[-1][0]
        for it, nm, mf in commits:
            if nm in keep:
                continue
            if it >= cutoff and not self._remote_complete(nm, mf):
                continue
            self._delete_remote(nm)

    def _delete_remote(self, name: str) -> None:
        # the commit object goes FIRST — the bundle becomes invisible
        # atomically; the blob sweep after it can tear harmlessly
        self._retrying("delete", self.client.delete,
                       self._key("commit", name))
        for prefix in (self._key("bundles", name) + "/",
                       self._key("shards", name) + "/"):
            for key in self._retrying("list", self.client.list,
                                      prefix):
                self._retrying("delete", self.client.delete, key)

    def retire(self) -> None:
        try:
            for _, name, _ in self._commits():
                self._delete_remote(name)
        except OSError as e:
            log.warning("resilience: could not retire remote bundles "
                        "(%s) — local cache retired anyway", e)
        super().retire()

    def describe(self) -> str:
        inner = getattr(self.client, "describe", None)
        where = inner() if callable(inner) else repr(self.client)
        return (f"{self.kind}:{where}/{self.namespace} "
                f"(cache {self.directory})")


# ======================================================================
# preemption notices
# ======================================================================
class PreemptionNotice:
    """One cluster maintenance announcement: when it arrived, how much
    time the platform granted before the kill, and through which
    channel (``signal`` / ``metadata`` / ``http`` / ``api`` /
    ``chaos_notice``). ``deadline_s=None`` means no enforced deadline
    (an operator drain)."""

    def __init__(self, deadline_s: Optional[float] = None,
                 kind: str = "api"):
        self.wall_t = time.time()
        self._t0 = time.monotonic()
        self.deadline_s = (None if deadline_s is None
                           else float(deadline_s))
        self.kind = str(kind)

    def remaining(self) -> Optional[float]:
        if self.deadline_s is None:
            return None
        return self.deadline_s - (time.monotonic() - self._t0)

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "deadline_s": self.deadline_s,
                "remaining_s": self.remaining(), "wall_t": self.wall_t}


class NoticePoller:
    """GCE/Borg-style maintenance-event watcher: a daemon thread polls
    a metadata source and converts the first maintenance announcement
    into ``ft.request_preemption(deadline_s, kind="metadata")`` — the
    job checkpoints and drains BEFORE the platform kill instead of
    recovering after it.

    Sources (either/both; first hit wins, then the poller stops):

    - ``file``: a path whose EXISTENCE is the event (the control
      socket/file-lease spelling a ``WorkerSupervisor`` uses, and the
      chaos drill's fake event). Contents may be a JSON object
      (``{"deadline_s": 30}``), a bare number of seconds, or empty
      (``default_deadline_s`` applies).
    - ``url``: polled with GET — the GCE metadata contract: a body of
      ``NONE`` (or an unreachable endpoint) means no event;
      ``TERMINATE``/``MIGRATE_ON_MAINTENANCE``-style bodies or a JSON
      object mean preempt.

    ``run_fit`` starts one automatically when
    ``DL4J_TPU_PREEMPT_NOTICE_FILE`` / ``DL4J_TPU_PREEMPT_METADATA_URL``
    are set, so any policy-driven fit honors cluster notices with zero
    code changes."""

    def __init__(self, ft: "FaultTolerance", *,
                 file: Optional[str] = None, url: Optional[str] = None,
                 poll_s: float = 0.2,
                 default_deadline_s: float = 30.0):
        if file is None and url is None:
            raise ValueError("NoticePoller needs a file or url source")
        self.ft = ft
        self.file = file
        self.url = url
        self.poll_s = float(poll_s)
        self.default_deadline_s = float(default_deadline_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.delivered = False

    @staticmethod
    def from_env(ft: "FaultTolerance",
                 env=None) -> Optional["NoticePoller"]:
        e = env if env is not None else os.environ
        file = e.get("DL4J_TPU_PREEMPT_NOTICE_FILE")
        url = e.get("DL4J_TPU_PREEMPT_METADATA_URL")
        if not file and not url:
            return None
        return NoticePoller(
            ft, file=file or None, url=url or None,
            poll_s=float(e.get("DL4J_TPU_PREEMPT_POLL_S", "0.2") or 0.2),
            default_deadline_s=float(
                e.get("DL4J_TPU_PREEMPT_DEADLINE_S", "30") or 30))

    # ---------------------------------------------------------- sources
    def _parse_body(self, body: str) -> Optional[float]:
        """deadline_s from a source body; None = default deadline.
        Raises ValueError for a no-event body."""
        body = (body or "").strip()
        if not body:
            return None
        try:
            obj = json.loads(body)
        except ValueError:
            if body.upper().startswith(("TERMINATE", "MIGRATE")):
                return None
            raise
        if isinstance(obj, dict):
            d = obj.get("deadline_s")
            return None if d is None else float(d)
        return float(obj)

    def check_once(self) -> bool:
        """One poll pass; True when a notice was delivered."""
        if self.file and os.path.exists(self.file):
            try:
                with open(self.file) as f:
                    deadline = self._parse_body(f.read())
            except (OSError, ValueError):
                deadline = None
            self._deliver(deadline)
            return True
        if self.url:
            try:
                import urllib.request

                with urllib.request.urlopen(self.url, timeout=2) as r:
                    body = r.read().decode("utf-8", "replace")
            except Exception:
                return False     # unreachable metadata = no event
            if body.strip().upper() in ("", "NONE", "FALSE", "0"):
                return False
            try:
                deadline = self._parse_body(body)
            except ValueError:
                return False
            self._deliver(deadline)
            return True
        return False

    def _deliver(self, deadline_s: Optional[float]) -> None:
        self.delivered = True
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        log.warning("resilience: maintenance notice from %s — "
                    "checkpoint-and-drain within %.1fs",
                    self.file or self.url, deadline_s)
        self.ft.request_preemption(deadline_s=deadline_s,
                                   kind="metadata")

    # ----------------------------------------------------------- thread
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.check_once():
                    return       # one-shot: the notice is delivered
            except Exception:
                log.exception("resilience: notice poller pass failed")
            self._stop.wait(self.poll_s)

    def start(self) -> "NoticePoller":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="NoticePoller")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(2.0)
            self._thread = None


# ======================================================================
# step watchdog
# ======================================================================
def _dump_stacks() -> str:
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    lines = []
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(lines)


class StepWatchdog:
    """Context manager arming a one-shot deadline around a training
    step. On expiry it does NOT kill the step (a first long step is
    usually a jit compile) — it dumps every thread's stack and a
    telemetry snapshot to the log and bumps the stall counter, which is
    exactly the evidence needed when a step is wedged on a dead host
    transfer or a hung collective.

    Cost: one short-lived daemon Timer thread per armed step (~tens of
    µs to start+cancel). Deadlines worth watching are seconds to
    minutes, so that's noise; a sub-millisecond-step workload that
    somehow wants a watchdog would upgrade to a persistent re-armed
    monitor thread."""

    def __init__(self, deadline: float, context: str = "train_step",
                 step: Optional[int] = None,
                 flight_dir: Optional[str] = None,
                 on_fire=None):
        self.deadline = float(deadline)
        self.context = context
        self.step = step
        self.flight_dir = flight_dir
        #: optional callback(watchdog) invoked on expiry — the control
        #: plane's stall verdict feed (no telemetry polling needed)
        self.on_fire = on_fire
        self._timer: Optional[threading.Timer] = None
        self.fired = False

    def _fire(self) -> None:
        self.fired = True
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.WATCHDOG_STALLS,
                "training steps that exceeded the watchdog deadline"
            ).inc(context=self.context)
        try:
            snap = json.dumps(_telemetry.snapshot())
        except Exception:
            snap = "<unavailable>"
        log.error(
            "WATCHDOG: %s exceeded its %.1fs deadline — still waiting. "
            "Thread stacks:\n%s\ntelemetry: %s",
            self.context, self.deadline, _dump_stacks(), snap)
        # the black box: everything leading UP to the stall. Dumped on
        # its own short-lived thread — the wedged step can't do it
        # itself, and the TIMER thread must stay prompt (its lifetime
        # is part of the watchdog's contract; the dump fsyncs)
        t = threading.Thread(
            target=_flight.incident, args=("watchdog_stall",),
            kwargs=dict(directory=self.flight_dir,
                        context=self.context, step=self.step,
                        deadline_s=self.deadline),
            name="FT-incident-dump", daemon=True)
        t.start()
        if self.on_fire is not None:
            try:
                self.on_fire(self)
            except Exception:
                log.exception("watchdog on_fire callback failed")

    def __enter__(self) -> "StepWatchdog":
        self._timer = threading.Timer(self.deadline, self._fire)
        self._timer.daemon = True
        self._timer.name = "FT-watchdog"
        self._timer.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


# ======================================================================
# the policy
# ======================================================================
class FaultTolerance:
    """Fault-tolerance policy for ``fit(..., fault_tolerance=...)``.

    Knobs (all optional — the defaults are a reasonable production
    posture; ``FaultTolerance()`` with no checkpoint_dir still gives
    the divergence guard + watchdog + transfer retry):

    - ``checkpoint_dir``: where preemption bundles live; also the
      auto-resume discovery root. None disables preemption checkpoints.
    - ``auto_resume``: restore the newest valid bundle before training
      (default True when a checkpoint_dir is set).
    - ``keep_last``: bundles retained (>=2 enables corruption fallback).
    - ``preemption_signals``: signals that trigger checkpoint-and-exit.
    - ``divergence_window``: rolling loss window length (0 = guard off).
    - ``spike_factor`` / ``min_history``: a finite loss is divergent
      when it exceeds ``median + spike_factor * max(|median|, 1e-3)``
      and at least ``min_history`` losses have been seen. NaN/Inf is
      always divergent.
    - ``snapshot_every``: steps between in-memory device snapshots
      (rollback granularity).
    - ``max_rollbacks``: rollback budget per fit before
      ``DivergenceError``.
    - ``transfer_retries`` / ``transfer_backoff``: applied to a
      ``DevicePrefetchIterator`` feeding the loop (no-op otherwise).
    - ``step_deadline``: per-step watchdog deadline in seconds
      (None = watchdog off).
    - ``compile_grace_s``: extra watchdog allowance for the FIRST step
      of each fit, which pays the jit compile (minutes on big models).
      Default 0 keeps the historical behavior — a short deadline fires
      on the compile step, which is harmless when the watchdog only
      dumps diagnostics. The JobScheduler arms a generous grace
      (``TrainJob(compile_grace_s=...)``) because there a stall verdict
      triggers a MIGRATION: without the grace, every fresh attempt's
      compile would read as a stall and the job would migrate forever.
    - ``flight_dir``: where flight-recorder incident dumps land
      (watchdog stall / divergence rollback / preemption — see
      profiler/flight_recorder.py). Defaults to
      ``<checkpoint_dir>/incidents`` when a checkpoint_dir is set,
      else the recorder's own default resolution.
    - ``checkpoint_every``: steps between PERIODIC resumable bundles
      (None = preemption-only, the pre-control-plane behavior).
      Periodic bundles are what make a SIGKILL-equivalent death
      (no grace period, no signal — the host just vanishes)
      recoverable: the newest digest-valid bundle restores and the
      run replays forward bit-identically from there. Requires a
      stateful iterator (``get_state``/``set_state``); stateless
      iterators skip periodic bundles with a one-time warning.
    - ``context``: watchdog/telemetry label for this policy's fits
      (the JobScheduler sets ``job:<id>`` so stall counters are
      per-job attributable).
    - ``on_stall``: optional callback(StepWatchdog) invoked from the
      watchdog's timer thread on deadline expiry — the control plane's
      stall-verdict feed.

    The object is reusable across fits — per-run state lives in a
    private ``_RunState`` created by ``run_fit``.
    """

    def __init__(self,
                 checkpoint_dir: Optional[str] = None,
                 auto_resume: bool = True,
                 keep_last: int = 2,
                 preemption_signals: Sequence[int] = (
                     signal.SIGTERM, signal.SIGINT),
                 divergence_window: int = 16,
                 spike_factor: float = 25.0,
                 min_history: int = 8,
                 snapshot_every: int = 10,
                 max_rollbacks: int = 8,
                 transfer_retries: int = 5,
                 transfer_backoff: float = 0.05,
                 step_deadline: Optional[float] = None,
                 compile_grace_s: float = 0.0,
                 flight_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 context: str = "train_step",
                 on_stall=None,
                 bundle_store: Optional[BundleStore] = None):
        self.bundle_store = bundle_store
        if bundle_store is not None:
            if checkpoint_dir \
                    and os.fspath(checkpoint_dir) != bundle_store.directory:
                # both given: the EXPLICIT store wins — silently
                # writing to a local dir would defeat the exact
                # survivor-discovery the store was configured for
                log.warning(
                    "FaultTolerance: both checkpoint_dir=%s and "
                    "bundle_store=%s were given — the bundle store "
                    "wins; bundles will NOT be written to the "
                    "checkpoint_dir", checkpoint_dir,
                    bundle_store.describe())
            # the store's directory doubles as the checkpoint anchor so
            # every "is checkpointing configured" gate (and the
            # incident-dir default) keeps working unchanged
            checkpoint_dir = bundle_store.directory
        self.checkpoint_dir = checkpoint_dir
        self.auto_resume = auto_resume
        self.keep_last = max(int(keep_last), 1)
        self.preemption_signals = tuple(preemption_signals)
        self.divergence_window = int(divergence_window)
        self.spike_factor = float(spike_factor)
        # the rolling window can never hold more than divergence_window
        # losses, so a min_history above it would silently disable the
        # spike rule — clamp so the configured guard is always live
        self.min_history = max(int(min_history), 1)
        if self.divergence_window > 0:
            self.min_history = min(self.min_history,
                                   self.divergence_window)
        self.snapshot_every = max(int(snapshot_every), 1)
        self.max_rollbacks = int(max_rollbacks)
        self.transfer_retries = int(transfer_retries)
        self.transfer_backoff = float(transfer_backoff)
        self.step_deadline = step_deadline
        self.compile_grace_s = float(compile_grace_s)
        self.flight_dir = flight_dir
        self.checkpoint_every = (int(checkpoint_every)
                                 if checkpoint_every else None)
        self.context = str(context)
        self.on_stall = on_stall
        self._preempt = threading.Event()
        # single-slot holders, not plain attributes: resolve_policy's
        # shallow copy shares the LIST objects (like _preempt), so an
        # inject_fault / preemption notice on the original lands in
        # the copy's running fit
        self._fault_box: List[Optional[BaseException]] = [None]
        self._notice_box: List[Optional[PreemptionNotice]] = [None]
        self._ckpt_count = [0]
        self._warned_stateless = False

    def incident_dir(self) -> Optional[str]:
        """Where this policy's incident dumps go; None defers to the
        flight recorder's default resolution."""
        if self.flight_dir:
            return self.flight_dir
        if self.checkpoint_dir:
            return os.path.join(self.checkpoint_dir, "incidents")
        return None

    # ------------------------------------------------------------ misc
    @property
    def preemption_requested(self) -> bool:
        return self._preempt.is_set()

    @property
    def notice(self) -> Optional[PreemptionNotice]:
        """The live preemption notice (None when preemption was never
        requested, or the last one was consumed by a checkpoint)."""
        return self._notice_box[0]

    @property
    def preemptions_checkpointed(self) -> int:
        """Preemption checkpoints this policy has written — how a
        caller (the worker runner, a drill) tells a drained-by-notice
        exit from a normal completion."""
        return self._ckpt_count[0]

    def store(self) -> Optional[BundleStore]:
        """The bundle store checkpoints go to / resume comes from:
        the explicit ``bundle_store`` when its directory is still the
        policy's checkpoint anchor, else a plain local store over
        ``checkpoint_dir`` (the historical behavior), else None."""
        if self.bundle_store is not None \
                and (not self.checkpoint_dir
                     or self.checkpoint_dir == self.bundle_store.directory):
            return self.bundle_store
        if self.checkpoint_dir:
            return LocalBundleStore(self.checkpoint_dir)
        return None

    def request_preemption(self, deadline_s: Optional[float] = None,
                           kind: str = "api") -> None:
        """Preemption notice: checkpoint ONE resumable bundle at the
        next step boundary, then exit the fit cleanly. Callable from
        any thread (the signal handler, a metadata poller, the
        scheduler, an HTTP handler). ``deadline_s`` is the platform's
        grace window — when notices stack, the EARLIEST absolute
        deadline wins; the checkpoint path records whether the bundle
        landed inside it. A notice whose window is shorter than a
        step cannot be honored in time — the kill lands first and
        recovery degrades to the newest periodic bundle (the
        SIGKILL-equivalent story)."""
        notice = PreemptionNotice(deadline_s, kind)
        prev = self._notice_box[0]
        mine, theirs = notice.remaining(), \
            prev.remaining() if prev is not None else None
        if prev is None or (mine is not None
                            and (theirs is None or mine < theirs)):
            self._notice_box[0] = notice
        _flight.record("preemption_notice", notice_kind=kind,
                       deadline_s=deadline_s, context=self.context)
        self._preempt.set()

    def inject_fault(self, exc: BaseException) -> None:
        """SIGKILL-equivalent fault injection: the fit loop raises
        ``exc`` at its next step boundary WITHOUT writing a checkpoint
        — unlike ``request_preemption``, nothing gets to clean up.
        The JobScheduler's kill-a-worker drill delivers device-loss
        this way (an in-process thread can't be hard-killed); recovery
        is the newest periodic bundle, exactly as after a real host
        death."""
        self._fault_box[0] = exc

    @contextlib.contextmanager
    def _signal_scope(self):
        """Install checkpoint-on-signal handlers for the duration of a
        fit; always restores the previous handlers. Signals can only be
        trapped on the main thread — elsewhere the loop still honors
        ``request_preemption()``, it just can't hook SIGTERM itself.

        The flag is deliberately NOT cleared on entry: a preemption
        notice that arrives before fit() (or during the auto-resume
        restore) must checkpoint at the FIRST step boundary, not be
        silently discarded. The loop clears it after acting on it."""
        if not self.preemption_signals \
                or threading.current_thread() is not threading.main_thread():
            yield
            return

        def _handler(signum, frame):
            if self._preempt.is_set():
                # second signal: the operator (or the platform's grace-
                # period enforcer) wants out NOW
                raise KeyboardInterrupt(
                    f"signal {signum} received twice during training")
            self.request_preemption(kind="signal")
            log.warning(
                "resilience: signal %s received — writing a resumable "
                "checkpoint at the next step boundary, then exiting",
                signum)

        prev = {}
        try:
            for s in self.preemption_signals:
                prev[s] = signal.signal(s, _handler)
        except (ValueError, OSError):
            pass   # restricted environment: proceed unhooked
        try:
            yield
        finally:
            for s, h in prev.items():
                if h is not None:   # None = handler installed at C
                    signal.signal(s, h)   # level; not restorable from
                #                           Python (signal.signal(s,
                #                           None) raises TypeError)

    def _watchdog(self, step: Optional[int] = None):
        if self.step_deadline is None:
            return contextlib.nullcontext()
        deadline = self.step_deadline
        if step == 0 and self.compile_grace_s > 0:
            # this run's first step pays the jit compile; a deadline
            # tuned for warm steps would misfire every (re)start
            deadline += self.compile_grace_s
        return StepWatchdog(deadline, context=self.context,
                            step=step, flight_dir=self.incident_dir(),
                            on_fire=self.on_stall)


def resolve_policy(fault_tolerance: Optional[FaultTolerance],
                   auto_resume) -> Optional[FaultTolerance]:
    """Merge the two fit kwargs into one policy. ``auto_resume=dir`` is
    the one-argument spelling of 'checkpoint here, resume from here'."""
    if fault_tolerance is None and auto_resume is None:
        return None
    ft = fault_tolerance if fault_tolerance is not None else FaultTolerance()
    if auto_resume:
        if fault_tolerance is not None:
            # never mutate the caller's policy object: it is documented
            # as reusable across fits, and a later fit passing only
            # fault_tolerance= must not inherit this call's resume dir.
            # A SHALLOW copy deliberately shares the _preempt Event so
            # ft.request_preemption() on the original still lands.
            import copy

            ft = copy.copy(fault_tolerance)
        if isinstance(auto_resume, (str, os.PathLike)):
            ft.checkpoint_dir = os.fspath(auto_resume)
        ft.auto_resume = True
    return ft


# ======================================================================
# model/trainer seam
# ======================================================================
class _FitAdapter:
    """Uniform step/snapshot/restore seam over the three fit
    front-ends (mirrors parallel/sharded.py's _ModelFuncs)."""

    def __init__(self, model, trainer=None):
        self.model = model
        self.trainer = trainer
        self.is_graph = hasattr(model, "params_map")

    # ------------------------------------------------------------ step
    def step(self, batch) -> None:
        from deeplearning4j_tpu.datasets.multi_dataset import MultiDataSet

        if self.trainer is not None:
            if isinstance(batch, MultiDataSet):
                self.trainer._fit_batch(list(batch.features),
                                        list(batch.labels),
                                        batch.labels_mask_arrays or None,
                                        batch.features_mask_arrays
                                        or None)
            else:
                self.trainer._fit_batch(batch.features, batch.labels,
                                        batch.labels_mask,
                                        batch.features_mask)
        elif self.is_graph:
            if isinstance(batch, MultiDataSet):
                self.model._fit_batch(batch.features, batch.labels,
                                      batch.labels_mask_arrays or None,
                                      batch.features_mask_arrays or None)
            else:
                self.model._fit_batch([batch.features], [batch.labels],
                                      [batch.labels_mask],
                                      [batch.features_mask])
        else:
            self.model._fit_batch(batch.features, batch.labels,
                                  batch.labels_mask, batch.features_mask)

    def end_epoch(self) -> None:
        m = self.model
        m._epoch += 1
        if self.trainer is None and not self.is_graph:
            # MultiLayerNetwork is the only front-end with epoch-end
            # listener callbacks (parity with its legacy loop)
            for l in m._listeners:
                if hasattr(l, "onEpochEnd"):
                    l.onEpochEnd(m)

    def finish(self) -> None:
        if self.trainer is not None and hasattr(self.trainer, "_finish"):
            self.trainer._finish()

    def invalidate_trainer_state(self) -> None:
        """After a bundle restore, a REUSED ShardedTrainer's per-shard
        replicas (averaging/compressed `_local`, `_residual`,
        `_thresholds`; zero-mode `_zero` flat masters/opt) still hold
        pre-restore values — drop them (and the compiled step, whose
        rebuild path re-derives them from the restored model trees —
        for zero mode that re-flatten IS the topology re-shard: the
        trees are replica-count-free, so a bundle saved on an 8-way
        mesh restores onto a 4-way trainer by re-placement). 'sharing'
        without update sharding keeps all state in the model trees, so
        a trainer with none built stays untouched and pays no
        recompile."""
        t = self.trainer
        if t is None:
            return
        if getattr(t, "_local", None) is not None \
                or getattr(t, "_residual", None) is not None \
                or getattr(t, "_zero", None) is not None:
            t._step = None
            t._sharing_steps = {}
            t._local = None
            t._residual = None
            t._thresholds = None
            t._zero = None
            t._zero_layout = None

    # ------------------------------------------------- snapshot/restore
    def _trees(self):
        m = self.model
        return (m.params_map, m.states_map) if self.is_graph \
            else (m.params_list, m.states_list)

    def snapshot(self) -> Dict[str, Any]:
        """Full in-memory training-state snapshot, on device. Copies
        are REQUIRED: the compiled steps donate param/opt buffers, so
        aliased references would be deleted by the very next step. The
        RNG key and score are step OUTPUTS/non-donated and safe to
        alias."""
        import jax
        import jax.numpy as jnp

        cp = lambda t: jax.tree_util.tree_map(jnp.copy, t)
        m = self.model
        params, states = self._trees()
        snap: Dict[str, Any] = {
            "iteration": m._iteration,
            "epoch": m._epoch,
            "rng": m._rng_key,
            "score": m._score,
            "params": cp(params),
            "states": cp(states),
            "opt": cp(m.opt_states),
        }
        if getattr(m, "_loss_scale_state", None) is not None:
            snap["ls"] = cp(m._loss_scale_state)
            snap["ls_seen"] = m._ls_seen
        if self.trainer is not None:
            for name in ("_residual", "_thresholds", "_local", "_zero"):
                v = getattr(self.trainer, name, None)
                if v is not None:
                    snap[name] = cp(v)
        return snap

    def restore(self, snap: Dict[str, Any]) -> None:
        """Install a snapshot (as fresh copies — the snapshot itself
        stays valid for a second rollback)."""
        import jax
        import jax.numpy as jnp

        cp = lambda t: jax.tree_util.tree_map(jnp.copy, t)
        m = self.model
        if self.is_graph:
            m.params_map, m.states_map = cp(snap["params"]), cp(snap["states"])
        else:
            m.params_list, m.states_list = (cp(snap["params"]),
                                            cp(snap["states"]))
        m.opt_states = cp(snap["opt"])
        m._iteration = snap["iteration"]
        m._epoch = snap["epoch"]
        m._rng_key = snap["rng"]
        m._score = snap["score"]
        if "ls" in snap:
            m._loss_scale_state = cp(snap["ls"])
            m._ls_seen = snap["ls_seen"]
        if self.trainer is not None:
            for name in ("_residual", "_thresholds", "_local", "_zero"):
                if name in snap:
                    setattr(self.trainer, name, cp(snap[name]))


class _RunState:
    def __init__(self, ft: FaultTolerance, adapter: "_FitAdapter"):
        self.steps_done = 0        # monotonic, survives rollbacks
        self.rollbacks = 0
        self.snapshot: Optional[Dict[str, Any]] = None
        self.since_snapshot = 0
        self.window: deque = deque(maxlen=max(ft.divergence_window, 1))
        #: layer label of the current non-finite event (HealthMonitor
        #: provenance) — rides the rollback telemetry, then clears
        self.nonfinite_layer: Optional[str] = None
        # mixed_float16 baseline: skipped-step count at fit entry, so
        # the guard can tell a HANDLED overflow (engine skipped the
        # step, halved the scale — params untouched) from divergence
        self.ls_skipped_seen = (_ls_skipped(adapter.model)
                                if ft.divergence_window > 0 else 0)


def _ls_skipped(model) -> int:
    """Device-side skipped-step counter of the dynamic loss-scale
    engine (0 for policies without loss scaling)."""
    ls = getattr(model, "_loss_scale_state", None)
    if ls is None:
        return 0
    return int(np.asarray(ls["skipped_steps"]))


# ======================================================================
# data plumbing
# ======================================================================
def _as_iterator(data, labels, adapter: _FitAdapter):
    """Normalize every fit input shape onto the iterator protocol.
    Returns (iterator, was_iterator) — epoch counters/listeners only
    advance for true iterator inputs, matching the legacy loops."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import (
        DataSetIterator, ListDataSetIterator,
    )
    from deeplearning4j_tpu.datasets.multi_dataset import (
        ListMultiDataSetIterator, MultiDataSet, MultiDataSetIterator,
    )
    from deeplearning4j_tpu.ndarray.ndarray import _unwrap

    if isinstance(data, (DataSetIterator, MultiDataSetIterator)):
        return data, True
    if isinstance(data, DataSet):
        return ListDataSetIterator([data]), False
    if isinstance(data, MultiDataSet):
        return ListMultiDataSetIterator([data]), False
    if labels is None:
        raise ValueError("fit(x, y) requires labels")
    if adapter.is_graph:
        xs = data if isinstance(data, (list, tuple)) else [data]
        ys = labels if isinstance(labels, (list, tuple)) else [labels]
        return ListMultiDataSetIterator([MultiDataSet(
            [_unwrap(x) for x in xs], [_unwrap(y) for y in ys])]), False
    return ListDataSetIterator(
        [DataSet(_unwrap(data), _unwrap(labels))]), False


def _try_get_state(it) -> Optional[Dict[str, Any]]:
    try:
        return it.get_state()
    except Exception:
        return None


def _try_set_state(it, state) -> bool:
    try:
        it.set_state(state)
        return True
    except Exception as e:
        log.warning("resilience: iterator %s could not restore mid-epoch "
                    "position (%s) — restarting the interrupted epoch "
                    "from its first batch", type(it).__name__, e)
        return False


# ======================================================================
# bundle <-> live model
# ======================================================================
def _rng_key_data(model) -> List[int]:
    import jax

    return [int(v) for v in
            np.asarray(jax.random.key_data(model._rng_key)).ravel()]


def _write_preemption_checkpoint(ft: FaultTolerance, adapter: _FitAdapter,
                                 it, epoch_idx: int, total_epochs: int,
                                 was_iterator: bool) -> None:
    ist = _try_get_state(it)   # non-blocking: reads recorded position
    if ist is not None:
        # deliberately NO it.hasNext() probe here: on a wedged or
        # retrying transfer pipeline hasNext() can block long past the
        # platform's kill grace period, and writing the bundle is the
        # one thing that must happen NOW. Whether the captured position
        # is mid-epoch or exactly at the epoch boundary is resolved at
        # RESUME time: a restored position with nothing left simply
        # completes an empty first epoch there, whose end-of-epoch
        # bookkeeping (epoch counter + onEpochEnd) runs as part of it —
        # including for a shuffling iterator, whose internal epoch
        # counter rides the state so the next reset() deals the same
        # permutation an uninterrupted run would have seen.
        remaining = total_epochs - epoch_idx
        mid = True
    else:
        # stateless iterator: a (possibly blocking) hasNext is the only
        # way to tell a finished epoch from an interrupted one
        try:
            has_more = bool(it.hasNext())
        except Exception:
            has_more = False
        if not has_more:
            if was_iterator:
                adapter.end_epoch()   # the epoch completed — book it
            remaining = total_epochs - epoch_idx - 1
        else:
            remaining = total_epochs - epoch_idx   # restart this epoch
            log.warning(
                "resilience: %s does not support state capture — the "
                "resumed run will RESTART the interrupted epoch from "
                "its first batch (batches already trained this epoch "
                "will be trained again); implement get_state/set_state "
                "for exact mid-epoch resume", type(it).__name__)
        mid = False
    adapter.finish()   # sync the sharded trainer's canonical trees
    store = ft.store()
    if store is None:
        log.warning("resilience: preemption requested but no "
                    "checkpoint_dir/bundle_store configured — exiting "
                    "WITHOUT a resumable checkpoint")
        return
    meta = {
        "rng": _rng_key_data(adapter.model),
        "iterator_state": ist,
        "epochs_remaining": max(remaining, 0),
        "mid_epoch": mid,
        "wall_time": time.time(),
    }
    path = store.write(adapter.model, meta, keep_last=ft.keep_last,
                       trainer=adapter.trainer)
    ft._ckpt_count[0] += 1
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.FT_PREEMPTION_CHECKPOINTS,
            "resumable bundles written in response to a preemption "
            "signal").inc()
    # deadline accounting: did the bundle land inside the notice's
    # grace window? A negative margin means the platform kill beat the
    # step boundary — this checkpoint is best-effort and recovery is
    # really the newest periodic bundle's job
    notice = ft.notice
    margin = notice.remaining() if notice is not None else None
    if notice is not None and notice.expired:
        log.warning(
            "resilience: preemption checkpoint landed %.2fs AFTER the "
            "%.1fs notice deadline — the platform kill may have "
            "preceded it; periodic bundles are the recovery floor",
            -margin, notice.deadline_s)
    # the bundle restores the run; the flight dump explains the exit —
    # written AFTER the bundle so a grace-period kill mid-dump still
    # leaves a resumable job
    _flight.incident("preemption_checkpoint",
                     directory=ft.incident_dir(),
                     iteration=adapter.model.getIterationCount(),
                     bundle=path,
                     epochs_remaining=meta["epochs_remaining"],
                     mid_epoch=mid,
                     notice_kind=(notice.kind if notice else None),
                     deadline_margin_s=margin,
                     deadline_missed=bool(notice and notice.expired))
    log.warning("resilience: preemption checkpoint written to %s "
                "(iteration %d, %d epoch(s) remaining%s) — exiting "
                "cleanly", path, adapter.model.getIterationCount(),
                meta["epochs_remaining"],
                ", mid-epoch" if mid else "")


def _write_periodic_checkpoint(ft: FaultTolerance, adapter: _FitAdapter,
                               it, epoch_idx: int, total_epochs: int
                               ) -> None:
    """Periodic resumable bundle (``checkpoint_every``): same atomic
    bundle as a preemption checkpoint, written in-stride — the fit
    keeps running. This is the recovery floor for deaths that never
    get a grace period (host loss, OOM-killer, chaos
    ``WorkerKilledError``): at most ``checkpoint_every`` steps are
    ever lost, and the replay from the bundle is bit-identical
    (RNG + iterator position + updater state all ride along)."""
    store = ft.store()
    if store is None:
        return
    ist = _try_get_state(it)
    if ist is None:
        if not ft._warned_stateless:
            ft._warned_stateless = True
            log.warning(
                "resilience: checkpoint_every=%d requested but %s has "
                "no get_state/set_state — periodic checkpoints are "
                "SKIPPED (preemption checkpoints still work; implement "
                "iterator state for kill-safe periodic bundles)",
                ft.checkpoint_every, type(it).__name__)
        return
    adapter.finish()   # sync the sharded trainer's canonical trees
    meta = {
        "rng": _rng_key_data(adapter.model),
        "iterator_state": ist,
        "epochs_remaining": max(total_epochs - epoch_idx, 0),
        "mid_epoch": True,
        "periodic": True,
        "wall_time": time.time(),
    }
    path = store.write(adapter.model, meta, keep_last=ft.keep_last,
                       trainer=adapter.trainer)
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.FT_PERIODIC_CHECKPOINTS,
            "periodic resumable bundles written every "
            "checkpoint_every steps").inc()
    _flight.record("periodic_checkpoint",
                   iteration=adapter.model.getIterationCount(),
                   bundle=path)


def _restore_bundle(adapter: _FitAdapter, path: str) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    with open(os.path.join(path, "resume.json")) as f:
        resume = json.load(f)
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            saved_mesh = json.load(f).get("mesh")
    except (OSError, ValueError):
        saved_mesh = None
    now_mesh = _mesh_topology(adapter.trainer)
    if saved_mesh and now_mesh and (
            saved_mesh.get("data") != now_mesh.get("data")
            or saved_mesh.get("processes") != now_mesh.get("processes")):
        # topology change (elastic resume): the canonical trees in
        # model.zip are replica-count-free; the trainer re-shards them
        # onto ITS mesh at the next step build (see
        # invalidate_trainer_state)
        log.warning(
            "resilience: bundle was saved on a %(od)s-replica/"
            "%(op)s-process mesh, restoring onto %(nd)s-replica/"
            "%(np)s-process — master/opt state will be re-sharded "
            "from the canonical trees",
            {"od": saved_mesh.get("data"),
             "op": saved_mesh.get("processes"),
             "nd": now_mesh.get("data"), "np": now_mesh.get("processes")})
    ModelSerializer.loadInto(adapter.model, os.path.join(path, "model.zip"))
    adapter.model._rng_key = jax.random.wrap_key_data(
        jnp.asarray(np.asarray(resume["rng"], np.uint32)))
    adapter.invalidate_trainer_state()
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.FT_AUTO_RESUMES,
            "training runs resumed from a preemption bundle").inc()
    _flight.record("auto_resume", bundle=path,
                   iteration=adapter.model.getIterationCount(),
                   epochs_remaining=resume.get("epochs_remaining", 0))
    log.warning("resilience: auto-resumed from %s (iteration %d, epoch "
                "%d, %d epoch(s) remaining%s)", path,
                adapter.model.getIterationCount(),
                adapter.model.getEpochCount(),
                resume.get("epochs_remaining", 0),
                ", mid-epoch" if resume.get("mid_epoch") else "")
    return resume


# ======================================================================
# guarded step helpers
# ======================================================================
def _maybe_snapshot(ft: FaultTolerance, adapter: _FitAdapter,
                    st: _RunState) -> None:
    if ft.divergence_window <= 0:
        return
    if st.snapshot is None or st.since_snapshot >= ft.snapshot_every:
        st.snapshot = adapter.snapshot()
        st.since_snapshot = 0


def _peek_loss_scale(model) -> Optional[float]:
    """Live loss-scale gauge value (already synced per step by the
    precision engine's telemetry mirror) — a host read, never a device
    sync. None when the model has no loss scaling. With several models
    loss-scaling in one process the gauge carries one label set per
    site and this returns the last-registered one — per-model
    attribution would need the site label threaded through the guard."""
    if getattr(model, "_loss_scale_state", None) is None:
        return None
    m = _telemetry.MetricsRegistry.get_default().peek(
        _telemetry.LOSS_SCALE)
    if m is None:
        return None
    vals = list(m.values().values())
    return vals[-1] if vals else None


def _check_divergence(ft: FaultTolerance, adapter: _FitAdapter,
                      st: _RunState) -> bool:
    """Post-step loss inspection. Returns True when the step was rolled
    back (the offending batch is skipped by simply not retrying it)."""
    if ft.divergence_window <= 0:
        return False
    loss = float(adapter.model._score)   # the guard's per-step sync
    # the guard pays the loss sync anyway — give the black box the
    # per-step loss (+ live loss scale) for free. Enabled-check HERE:
    # the kwargs (registry peek, iteration read) must not be evaluated
    # on a disabled recorder's behalf
    if _flight.get_default().enabled:
        _flight.record("train_loss", step=st.steps_done,
                       iteration=adapter.model.getIterationCount(),
                       loss=loss,
                       loss_scale=_peek_loss_scale(adapter.model))
    bad = not np.isfinite(loss)
    why = "non-finite loss"
    if bad:
        # NaN provenance: a HealthMonitor on the model knows WHICH
        # layer went non-finite this step (profiler/model_health.py) —
        # carry the label on the rollback event instead of making the
        # operator rerun with panic modes to find it
        hm = getattr(adapter.model, "_health", None)
        if hm is not None:
            try:
                layer = hm.nonfinite_label()
            except Exception:
                layer = None
            if layer is not None:
                why = f"non-finite loss (first non-finite layer: {layer})"
                st.nonfinite_layer = layer
        skipped = _ls_skipped(adapter.model)
        if skipped > st.ls_skipped_seen:
            # mixed_float16 handled overflow: the loss-scale engine
            # already skipped this step (params/opt-state held) and
            # halved the scale — that is the precision engine working,
            # not divergence. Rolling back here would reinstate the
            # PRE-halving scale and discard good committed steps.
            st.ls_skipped_seen = skipped
            st.since_snapshot += 1
            return False
    if not bad and len(st.window) >= ft.min_history:
        med = statistics.median(st.window)
        if (loss - med) > ft.spike_factor * max(abs(med), 1e-3):
            bad = True
            why = (f"loss spike {loss:.6g} vs rolling median {med:.6g} "
                   f"(factor {ft.spike_factor:g})")
    if not bad:
        st.window.append(loss)
        st.since_snapshot += 1
        return False
    if st.rollbacks >= ft.max_rollbacks:
        # budget exhausted: still restore the last good snapshot (a
        # caller catching DivergenceError to salvage the run must not
        # be handed diverged/NaN params), but don't count a rollback
        # that is really an abort
        bad_iter = adapter.model.getIterationCount()
        _flight.incident("divergence_abort",
                         directory=ft.incident_dir(),
                         iteration=bad_iter, why=why,
                         nonfinite_layer=st.nonfinite_layer,
                         rollbacks=st.rollbacks)
        adapter.restore(st.snapshot)
        raise DivergenceError(
            f"divergence guard exhausted its rollback budget "
            f"({ft.max_rollbacks}): {why} at iteration {bad_iter} — "
            "the run is not recovering (check the data pipeline and "
            "learning rate); model restored to the last snapshot "
            f"(iteration {st.snapshot['iteration']})")
    st.rollbacks += 1
    if _telemetry.enabled():
        reg = _telemetry.MetricsRegistry.get_default()
        labels = ({"nonfinite_layer": st.nonfinite_layer}
                  if st.nonfinite_layer else {})
        reg.counter(_telemetry.FT_ROLLBACKS,
                    "divergence-guard rollbacks to the in-memory "
                    "snapshot").inc(**labels)
        reg.counter(_telemetry.FT_SKIPPED_BATCHES,
                    "batches skipped after a divergence rollback").inc()
    layer = st.nonfinite_layer
    st.nonfinite_layer = None   # provenance is per-event, not sticky
    discarded = adapter.model.getIterationCount() - 1 \
        - st.snapshot["iteration"]
    log.warning("resilience: %s at iteration %d — rolling back to the "
                "snapshot at iteration %d and skipping the batch "
                "(rollback %d/%d; %d committed step(s) since the "
                "snapshot are discarded and their batches not "
                "replayed — lower snapshot_every for finer-grained "
                "rollback)", why, adapter.model.getIterationCount(),
                st.snapshot["iteration"], st.rollbacks, ft.max_rollbacks,
                max(discarded, 0))
    # post-mortem artifact: the black box holds the steps INTO the
    # divergence (losses, health provenance, the offending step last)
    _flight.incident("divergence_rollback", directory=ft.incident_dir(),
                     iteration=adapter.model.getIterationCount(),
                     rollback_to=st.snapshot["iteration"], why=why,
                     nonfinite_layer=layer, rollback=st.rollbacks)
    adapter.restore(st.snapshot)
    st.since_snapshot = 0
    # the restore rewound the loss-scale engine's counters with the
    # rest of the state — re-baseline so the next handled overflow
    # still reads as a fresh increment
    st.ls_skipped_seen = _ls_skipped(adapter.model)
    return True


# ======================================================================
# the guarded fit loop
# ======================================================================
def run_fit(model, fault_tolerance: Optional[FaultTolerance], data,
            labels=None, epochs: int = 1, auto_resume=None, trainer=None):
    """Fault-tolerant replacement for the legacy fit loops — entered by
    MultiLayerNetwork/ComputationGraph/ShardedTrainer ``fit`` ONLY when
    a policy was requested; the legacy paths stay untouched."""
    ft = resolve_policy(fault_tolerance, auto_resume)
    if ft is None:
        raise ValueError("run_fit requires a FaultTolerance policy or "
                         "an auto_resume directory")
    # black-box coverage: a crash that escapes every guard still
    # leaves an incident dump behind
    _flight.install_excepthook()
    adapter = _FitAdapter(model, trainer)
    it, was_iterator = _as_iterator(data, labels, adapter)
    try:
        resettable = bool(it.resetSupported())
    except Exception:
        resettable = True
    if int(epochs) > 1 and not resettable:
        # legacy parity (graph.py multi-epoch guard): fail fast with a
        # clear error instead of a raw NotImplementedError at epoch 2
        raise ValueError(
            "epochs > 1 requires a resettable iterator "
            "(reference behavior)")
    prev_retry = _configure_prefetch_retry(ft, it)
    # cluster-notice wiring (metadata-poll stub): a maintenance event
    # announced through the env-configured source preempts this fit
    poller = NoticePoller.from_env(ft)
    if poller is not None:
        poller.start()

    resumed = None
    store = ft.store()
    if ft.auto_resume and store is not None:
        bundle = store.latest_valid()
        if bundle is not None:
            resumed = _restore_bundle(adapter, bundle)

    total = int(epochs)
    skip_reset_first = False
    if resumed is not None:
        total = int(resumed.get("epochs_remaining", epochs))
        ist = resumed.get("iterator_state")
        if ist is not None:
            # mid-epoch: continue in place (no reset) on the next
            # batch. Epoch boundary: restore anyway — the epoch-opening
            # reset() below then advances the iterator's internal epoch
            # counter, keeping shuffle order identical to a run that
            # was never interrupted
            ok = _try_set_state(it, ist)
            skip_reset_first = ok and bool(resumed.get("mid_epoch"))
        elif total > 0:
            log.warning(
                "resilience: the bundle carries no iterator position "
                "(the interrupted run's iterator had no state support) "
                "— restarting the interrupted epoch from its first "
                "batch")

    # _last_etl_ms parity with the legacy MLN loop: a real ETL series
    # only for true iterator inputs; array/DataSet fits clear any stale
    # value (the UI would otherwise chart a frozen constant)
    track_etl = (was_iterator and trainer is None and not adapter.is_graph)
    if not was_iterator and trainer is None and not adapter.is_graph:
        model._last_etl_ms = None

    st = _RunState(ft, adapter)
    try:
        with ft._signal_scope():
            for e in range(total):
                # mirror MultiDataSetIterator.__iter__: a one-epoch fit
                # over a non-resettable stream consumes it in place
                if not (skip_reset_first and e == 0) and resettable:
                    it.reset()
                if _run_epoch(ft, adapter, it, st, e, total,
                              was_iterator, track_etl):
                    return model   # preempted: checkpointed clean exit
                if was_iterator:
                    adapter.end_epoch()
    finally:
        if poller is not None:
            poller.stop()
        if prev_retry is not None:
            # the retry posture belongs to THIS policy-driven fit: a
            # later plain fit() on the same iterator must get the
            # legacy fail-fast behavior back
            it.configure_retries(*prev_retry)
    adapter.finish()
    if ft.auto_resume and store is not None:
        # the run finished: retire its bundles so the next fit on this
        # directory starts fresh instead of reviving a completed run
        store.retire()
    return model


def _configure_prefetch_retry(ft: FaultTolerance, it):
    """Apply the policy's transfer-retry posture to a wrapping
    DevicePrefetchIterator. Returns the iterator's previous
    (retries, backoff, quarantine) for restoration at fit exit, or
    None when nothing was changed."""
    from deeplearning4j_tpu.datasets.device_prefetch import (
        DevicePrefetchIterator,
    )

    if isinstance(it, DevicePrefetchIterator) and ft.transfer_retries > 0 \
            and it._transfer_retries == 0 and not it._quarantine:
        # the user didn't configure their own retry posture — apply the
        # policy's (retry with backoff, then quarantine instead of die)
        prev = (it._transfer_retries, it._transfer_backoff,
                it._quarantine)
        it.configure_retries(ft.transfer_retries,
                             backoff=ft.transfer_backoff,
                             quarantine=True)
        return prev
    return None


def _run_epoch(ft: FaultTolerance, adapter: _FitAdapter, it,
               st: _RunState, epoch_idx: int, total_epochs: int,
               was_iterator: bool = True, track_etl: bool = False) -> bool:
    """One epoch under the guards. Returns True on preemption exit."""
    monkey = _chaos.active()
    while True:
        # the watchdog spans the whole fetch->step->guard cycle, not
        # just the step dispatch: the step itself is ASYNC (a hung
        # collective or wedged transfer surfaces at the next blocking
        # point — the iterator's queue get or the divergence guard's
        # loss sync), so arming only around adapter.step would never
        # fire for exactly the stalls the watchdog exists to diagnose
        with ft._watchdog(step=st.steps_done):
            t0 = time.perf_counter()
            if not it.hasNext():
                return False
            batch = it.next()
            _telemetry.record_phase("etl_wait", t0)
            if track_etl:
                # UI parity with the legacy MultiLayerNetwork loop: the
                # ETL wait feeds the system charts via _last_etl_ms
                adapter.model._last_etl_ms = \
                    (time.perf_counter() - t0) * 1e3
            if monkey is not None:
                batch = monkey.corrupt_batch(batch, st.steps_done)
            _maybe_snapshot(ft, adapter, st)
            adapter.step(batch)
            st.steps_done += 1
            if monkey is not None:
                # inside the watchdog scope on purpose: the injected
                # hang must trip the deadline like a real wedged step
                monkey.maybe_hang(st.steps_done)
            _check_divergence(ft, adapter, st)
        if monkey is not None:
            monkey.maybe_kill(st.steps_done)   # raises: no checkpoint
            monkey.maybe_preempt(st.steps_done, ft=ft)
        fault = ft._fault_box[0]
        if fault is not None:
            # SIGKILL-equivalent (inject_fault): die with NO
            # checkpoint — recovery is the newest periodic bundle
            ft._fault_box[0] = None
            raise fault
        if ft.preemption_requested:
            _write_preemption_checkpoint(ft, adapter, it, epoch_idx,
                                         total_epochs, was_iterator)
            # consumed: the next fit on this (reusable) policy object
            # must not re-preempt off a flag (or notice) already
            # acted on
            ft._preempt.clear()
            ft._notice_box[0] = None
            return True
        if ft.checkpoint_every \
                and st.steps_done % ft.checkpoint_every == 0:
            _write_periodic_checkpoint(ft, adapter, it, epoch_idx,
                                       total_epochs)


__all__ = ["FaultTolerance", "DivergenceError", "StepWatchdog",
           "run_fit", "resolve_policy", "write_bundle",
           "latest_valid_bundle", "validate_bundle", "retire_bundles",
           "BundleStore", "LocalBundleStore", "SharedFSBundleStore",
           "ObjectStoreBundleStore", "InMemoryObjectStore",
           "LocalObjectStore", "PreemptionNotice", "NoticePoller",
           "publish_foreign_shard"]
