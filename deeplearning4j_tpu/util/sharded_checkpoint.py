"""Multi-host sharded checkpointing + data-iterator state.

Reference role: SURVEY.md §5 names the TPU analog of the reference's
ModelSerializer/CheckpointListener for distributed runs explicitly —
"Orbax-style checkpoint of param/opt pytrees + data-iterator state".
Design (the Orbax pattern, no Orbax dependency):

- every process writes ONLY its addressable shards to a process-local
  ``shards_p{process_index}.npz`` (atomic tmp+rename), so checkpoint
  bandwidth scales with hosts and no host ever materializes the global
  array;
- process 0 writes ``manifest.json`` with the tree paths, global
  shapes/dtypes, step, process count, and the (JSON) iterator state;
- restore takes a TEMPLATE pytree carrying the target shardings (a
  freshly initialized model), loads each device's shard locally and
  reassembles global arrays with make_array_from_single_device_arrays
  — the same restore-args contract Orbax uses. Fully-replicated leaves
  are stored once per process, not once per device.

Works identically for a single process (the degenerate 1-host case is
the plain save path), so it composes with ModelSerializer artifacts.
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.util.model_serializer import (
    _flatten_with_paths, _unflatten_into,
)

_REP_KEY = "@@rep"
_STEP_KEY = "__step__"


class ShardedCheckpoint:
    FORMAT = "deeplearning4j_tpu-sharded-1"

    @staticmethod
    def save(dirpath: str, tree: Any, step: int = 0,
             iterator_state: Optional[Dict[str, Any]] = None) -> None:
        """Write this process's shards (+ manifest on process 0)."""
        os.makedirs(dirpath, exist_ok=True)
        pidx = jax.process_index()
        flat = _flatten_with_paths(tree, to_numpy=False)
        local: Dict[str, np.ndarray] = {}
        meta_paths: Dict[str, Dict[str, Any]] = {}
        for path, arr in flat.items():
            arr = jax.device_put(arr) if not isinstance(arr, jax.Array) \
                else arr
            meta_paths[path] = {"shape": list(arr.shape),
                                "dtype": str(arr.dtype)}
            if arr.is_fully_replicated:
                local[path + _REP_KEY] = np.asarray(
                    arr.addressable_shards[0].data)
            else:
                for sh in arr.addressable_shards:
                    local[f"{path}@@{sh.device.id}"] = np.asarray(sh.data)
        # every shard file embeds the step it belongs to: per-file
        # os.replace is atomic, but the MULTI-file checkpoint is not —
        # a crash between hosts' writes must be a loud restore error
        # (mixed-step shards), never silently mixed parameter state
        local[_STEP_KEY] = np.asarray(int(step), np.int64)
        buf = io.BytesIO()
        np.savez(buf, **local)
        tmp = os.path.join(dirpath, f".shards_p{pidx}.npz.tmp")
        final = os.path.join(dirpath, f"shards_p{pidx}.npz")
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
        os.replace(tmp, final)  # atomic: a killed run never leaves a
        # half-written shard file under the final name
        if pidx == 0:
            manifest = {
                "format": ShardedCheckpoint.FORMAT,
                "step": int(step),
                "num_processes": jax.process_count(),
                "paths": meta_paths,
                "iterator_state": iterator_state,
            }
            mtmp = os.path.join(dirpath, ".manifest.json.tmp")
            with open(mtmp, "w") as f:
                json.dump(manifest, f)
            os.replace(mtmp, os.path.join(dirpath, "manifest.json"))

    @staticmethod
    def restore(dirpath: str,
                template: Any) -> Tuple[Any, Dict[str, Any]]:
        """Rebuild the tree onto `template`'s shardings. Returns
        (tree, meta) where meta carries step + iterator_state."""
        with open(os.path.join(dirpath, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest["format"] != ShardedCheckpoint.FORMAT:
            raise ValueError(
                f"not a sharded checkpoint: {manifest['format']!r}")
        nproc = jax.process_count()
        if manifest["num_processes"] != nproc:
            raise ValueError(
                f"checkpoint written by {manifest['num_processes']} "
                f"processes, restoring with {nproc} (elastic reshape "
                "requires same topology)")
        pidx = jax.process_index()
        shards = np.load(os.path.join(dirpath, f"shards_p{pidx}.npz"))
        if _STEP_KEY in shards and \
                int(shards[_STEP_KEY]) != int(manifest["step"]):
            raise ValueError(
                f"checkpoint is torn: this host's shard file is from "
                f"step {int(shards[_STEP_KEY])} but the manifest says "
                f"step {manifest['step']} (a save crashed between "
                "hosts' writes; fall back to an older checkpoint)")
        flat_t = _flatten_with_paths(template, to_numpy=False)
        flat_out: Dict[str, Any] = {}
        for path, tarr in flat_t.items():
            info = manifest["paths"].get(path)
            if info is None:
                raise KeyError(f"checkpoint missing array {path!r}")
            tarr = jax.device_put(tarr) \
                if not isinstance(tarr, jax.Array) else tarr
            if tuple(info["shape"]) != tuple(tarr.shape):
                raise ValueError(
                    f"{path}: checkpoint shape {info['shape']} != "
                    f"template {tuple(tarr.shape)}")
            if tarr.is_fully_replicated and path + _REP_KEY in shards:
                data = shards[path + _REP_KEY]
                flat_out[path] = jax.make_array_from_callback(
                    tarr.shape, tarr.sharding, lambda idx, d=data: d[idx])
            else:
                bufs = []
                for sh in tarr.addressable_shards:
                    key = f"{path}@@{sh.device.id}"
                    if key not in shards:
                        raise KeyError(
                            f"{path}: no shard for device "
                            f"{sh.device.id} in this process's file "
                            "(device ids changed across restart?)")
                    bufs.append(jax.device_put(shards[key], sh.device))
                flat_out[path] = \
                    jax.make_array_from_single_device_arrays(
                        tarr.shape, tarr.sharding, bufs)
        tree = _unflatten_into(template, flat_out,
                               leaf_fn=lambda v: v)
        return tree, {"step": manifest["step"],
                      "iterator_state": manifest.get("iterator_state")}

    @staticmethod
    def exists(dirpath: str) -> bool:
        return os.path.exists(os.path.join(dirpath, "manifest.json"))


# -------------------------------------------------- model-tree helpers
def model_checkpoint_tree(model) -> Dict[str, Any]:
    """The complete training-state pytree of a MultiLayerNetwork /
    ComputationGraph for ``ShardedCheckpoint.save``: params,
    non-trainable state (BN stats), updater state, and — when the
    conf's precision policy uses dynamic loss scaling — the live
    loss-scale state, so a resumed mixed_float16 run keeps its scale
    and overflow counters instead of re-warming from the preset."""
    is_graph = hasattr(model, "params_map")
    tree: Dict[str, Any] = {
        "params": model.params_map if is_graph else model.params_list,
        "states": model.states_map if is_graph else model.states_list,
        "opt": model.opt_states,
    }
    if getattr(model, "_loss_scale_state", None) is not None:
        tree["loss_scale"] = model._loss_scale_state
    return tree


def save_model(dirpath: str, model, step: int = 0,
               iterator_state: Optional[Dict[str, Any]] = None) -> None:
    """``ShardedCheckpoint.save`` over ``model_checkpoint_tree``."""
    ShardedCheckpoint.save(dirpath, model_checkpoint_tree(model),
                           step=step, iterator_state=iterator_state)


def restore_model(dirpath: str, model) -> Dict[str, Any]:
    """Restore a sharded checkpoint INTO an initialized model (its
    current trees are the sharding template). Returns the checkpoint
    meta ({step, iterator_state})."""
    template = model_checkpoint_tree(model)
    tree, meta = ShardedCheckpoint.restore(dirpath, template)
    if hasattr(model, "params_map"):
        model.params_map = tree["params"]
        model.states_map = tree["states"]
    else:
        model.params_list = tree["params"]
        model.states_list = tree["states"]
    model.opt_states = tree["opt"]
    if "loss_scale" in tree:
        model._loss_scale_state = tree["loss_scale"]
        # keep the telemetry delta baseline in step with the restored
        # counters (see model_serializer._restore_loss_scale)
        model._ls_seen = (
            int(np.asarray(tree["loss_scale"]["overflows"])),
            int(np.asarray(tree["loss_scale"]["skipped_steps"])))
    return meta
