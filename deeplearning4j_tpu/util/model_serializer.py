"""Model serialization (reference: org/deeplearning4j/util/
ModelSerializer.java — zip of configuration.json + coefficients.bin +
updaterState.bin + optional normalizer; exact resume including optimizer
state. SURVEY.md §2.24, §5 checkpoint/resume).

Same zip layout, TPU-native payloads:
- configuration.json — the MultiLayerConfiguration JSON round-trip
- coefficients.npz   — per-layer param arrays, keys "<idx>/<name>"
- state.npz          — non-trainable layer state (BN running stats)
- updaterState.npz   — updater state pytree, flattened with path keys
- meta.json          — iteration/epoch counters, framework version

Exact-resume contract: load → continue training with bit-identical
updater behavior (tested in tests/test_serialization.py).
"""

from __future__ import annotations

import io
import json
import os
import uuid
import zipfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def fsync_directory(dirpath: str) -> None:
    """fsync a directory so a just-completed rename inside it survives
    power loss (POSIX: the rename itself is atomic, but its DURABILITY
    needs the directory entry flushed). Best-effort on platforms whose
    directories can't be opened (Windows)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_replace(tmp: str, path: str) -> None:
    """Durable atomic publish: fsync the temp file's bytes, rename it
    over ``path``, then fsync the directory entry. After this returns,
    a crash at ANY point leaves either the old file or the complete new
    one — never a truncated hybrid, and never a rename that a power cut
    silently un-does."""
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    fsync_directory(os.path.dirname(os.path.abspath(path)))


def unique_tmp_path(path: str) -> str:
    """Sibling temp name no other writer can collide with: two
    processes checkpointing the same target used to share one
    ``path + '.tmp'`` and clobber each other's half-written zip."""
    return f"{path}.{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp"


def _flatten_with_paths(tree, prefix="", to_numpy=True):
    """Flatten a pytree of arrays to {path: array} with '/'-joined keys.

    to_numpy=False keeps leaves as-is — required for multi-host sharded
    jax.Arrays, where np.asarray would try to fetch non-addressable
    shards (ShardedCheckpoint's path)."""
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_flatten_with_paths(tree[k], f"{prefix}{k}/",
                                           to_numpy))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_with_paths(v, f"{prefix}{i}/", to_numpy))
    elif tree is None:
        pass
    else:
        out[prefix[:-1]] = np.asarray(tree) if to_numpy else tree
    return out


def _unflatten_into(template, flat, prefix="", leaf_fn=None):
    """Rebuild arrays into the shape of `template` from {path: array}.
    leaf_fn converts each looked-up value (default jnp.asarray;
    identity for pre-built sharded jax.Arrays)."""
    if leaf_fn is None:
        leaf_fn = jnp.asarray
    if isinstance(template, dict):
        return {k: _unflatten_into(template[k], flat, f"{prefix}{k}/",
                                   leaf_fn)
                for k in template}
    if isinstance(template, tuple):
        return tuple(_unflatten_into(v, flat, f"{prefix}{i}/", leaf_fn)
                     for i, v in enumerate(template))
    if isinstance(template, list):
        return [_unflatten_into(v, flat, f"{prefix}{i}/", leaf_fn)
                for i, v in enumerate(template)]
    if template is None:
        return None
    return leaf_fn(flat[prefix[:-1]])


_UINT_BY_SIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _write_npz(zf: zipfile.ZipFile, name: str, arrays: dict):
    # numpy's npz format can't round-trip ml_dtypes (bfloat16, float8_*):
    # np.load hands back void '|V2' buffers. Store such arrays as a
    # same-width uint view and append '__as__<dtype>' to the key.
    enc = {}
    for k, a in arrays.items():
        if a.dtype.kind not in "biufc":
            enc[f"{k}__as__{a.dtype.name}"] = a.view(
                _UINT_BY_SIZE[a.dtype.itemsize])
        else:
            enc[k] = a
    buf = io.BytesIO()
    np.savez(buf, **enc)
    zf.writestr(name, buf.getvalue())


def _decode_dtype(name: str) -> np.dtype:
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError):
        return np.dtype(name)


def _read_npz(zf: zipfile.ZipFile, name: str) -> dict:
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        out = {}
        for k in data.files:
            if "__as__" in k:
                # the suffix only marks OUR dtype tag; a user-chosen
                # vertex name may legitimately contain '__as__', in
                # which case the suffix won't decode as a dtype
                key, dt = k.rsplit("__as__", 1)
                try:
                    out[key] = data[k].view(_decode_dtype(dt))
                    continue
                except TypeError:
                    pass
            out[k] = data[k]
        return out


def _normalizer_registry():
    """Zero-arg-constructible normalizer types restoreNormalizer can
    rebuild — the single source of truth for save-time validation."""
    from deeplearning4j_tpu.datasets.normalizers import (
        ImagePreProcessingScaler, MultiNormalizerMinMaxScaler,
        MultiNormalizerStandardize, NormalizerMinMaxScaler,
        NormalizerStandardize, VGG16ImagePreProcessor)

    return {"NormalizerStandardize": NormalizerStandardize,
            "NormalizerMinMaxScaler": NormalizerMinMaxScaler,
            "ImagePreProcessingScaler": ImagePreProcessingScaler,
            "VGG16ImagePreProcessor": VGG16ImagePreProcessor,
            "MultiNormalizerStandardize": MultiNormalizerStandardize,
            "MultiNormalizerMinMaxScaler": MultiNormalizerMinMaxScaler}


def _check_composite_children(normalizer) -> None:
    """A CompositeDataSetPreProcessor whose children are nested
    composites or unknown types saves fine but crashes on restore (the
    one-level 'p<i>/<key>' state paths cannot represent nesting and the
    restore registry rebuilds children with zero args) — reject at save
    time instead of at the much later, much more confusing restore."""
    registry = _normalizer_registry()
    for i, child in enumerate(normalizer.preprocessors):
        name = type(child).__name__
        if hasattr(child, "preprocessors"):
            raise ValueError(
                f"cannot save CompositeDataSetPreProcessor child {i} "
                f"({name}): nested composites are not restorable — "
                "flatten the children into one composite")
        if name not in registry:
            raise ValueError(
                f"cannot save CompositeDataSetPreProcessor child {i}: "
                f"{name} is not a restorable normalizer type "
                f"(expected one of {sorted(registry)})")


def _restore_loss_scale(zf: zipfile.ZipFile, model) -> None:
    """Load lossScaleState.npz into a freshly init()ed model. The
    init() template exists whenever the conf carries a loss-scaling
    precision policy; archives without the member (pre-policy saves or
    non-scaling policies) restore to the fresh state unchanged."""
    if "lossScaleState.npz" not in zf.namelist():
        return
    if getattr(model, "_loss_scale_state", None) is None:
        return  # conf has no scaling policy; ignore the stray member
    flat = _read_npz(zf, "lossScaleState.npz")
    model._loss_scale_state = _unflatten_into(
        model._loss_scale_state, flat)
    # telemetry baseline follows the restored counters: without this,
    # the first post-restore step would replay the checkpoint's whole
    # overflow history into the process counters as one spurious jump
    model._ls_seen = (int(flat.get("overflows", 0)),
                      int(flat.get("skipped_steps", 0)))


class ModelSerializer:
    @staticmethod
    def writeModel(model, path: str, save_updater: bool = True,
                   normalizer=None) -> None:
        """Reference: ModelSerializer.writeModel(model, file, saveUpdater)."""
        if normalizer is not None and hasattr(normalizer, "preprocessors"):
            # validate BEFORE any bytes hit disk — raising mid-zip
            # would leave a corrupt archive at path
            _check_composite_children(normalizer)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        is_graph = hasattr(model, "params_map")
        params = model.params_map if is_graph else model.params_list
        states = model.states_map if is_graph else model.states_list
        # atomic + crash-durable: serialize to a writer-unique temp
        # (pid+uuid — concurrent writers targeting the same path can't
        # clobber each other's temp), fsync, rename over path, fsync
        # the directory. A reader never observes a partial zip.
        tmp = unique_tmp_path(path)
        try:
            ModelSerializer._write_zip(model, tmp, save_updater,
                                       normalizer, params, states)
            atomic_replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    @staticmethod
    def _write_zip(model, path, save_updater, normalizer, params,
                   states) -> None:
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            _write_npz(zf, "coefficients.npz", _flatten_with_paths(params))
            _write_npz(zf, "state.npz", _flatten_with_paths(states))
            if save_updater and model.opt_states is not None:
                _write_npz(zf, "updaterState.npz",
                           _flatten_with_paths(model.opt_states))
            # dynamic loss-scale state (mixed_float16 policies): exact
            # resume keeps the live scale + overflow counters, so a
            # restored run neither re-warms the scale from scratch nor
            # forgets its overflow history (the policy itself rides in
            # configuration.json)
            if getattr(model, "_loss_scale_state", None) is not None:
                _write_npz(zf, "lossScaleState.npz",
                           _flatten_with_paths(model._loss_scale_state))
            meta = {"iteration": model.getIterationCount(),
                    "epoch": model.getEpochCount(),
                    "format": "deeplearning4j_tpu-1",
                    "model_type": type(model).__name__}
            zf.writestr("meta.json", json.dumps(meta))
            if normalizer is not None:
                info = {"type": type(normalizer).__name__}
                if hasattr(normalizer, "preprocessors"):  # composite
                    info["children"] = [type(p).__name__
                                        for p in normalizer.preprocessors]
                _write_npz(zf, "normalizer.npz",
                           _flatten_with_paths(normalizer.state_dict()))
                zf.writestr("normalizer.json", json.dumps(info))

    @staticmethod
    def loadInto(model, path: str, load_updater: bool = True):
        """Restore a saved archive INTO an already-initialized model of
        the matching architecture (the FaultTolerance auto-resume path:
        the caller owns the instance whose training should continue, so
        building a second one just to copy trees out of it would double
        peak memory). Overwrites params / non-trainable state / updater
        state / loss-scale state / iteration+epoch counters in place."""
        with zipfile.ZipFile(path) as zf:
            return ModelSerializer._load_members(model, zf, load_updater)

    @staticmethod
    def _load_members(model, zf: zipfile.ZipFile, load_updater: bool):
        coeff = _read_npz(zf, "coefficients.npz")
        states = _read_npz(zf, "state.npz")
        if hasattr(model, "params_map"):
            model.params_map = _unflatten_into(model.params_map, coeff)
            if states:
                model.states_map = _unflatten_into(
                    model.states_map, states)
        else:
            model.params_list = _unflatten_into(
                model.params_list, coeff)
            if states:
                model.states_list = _unflatten_into(
                    model.states_list, states)
        if load_updater and "updaterState.npz" in zf.namelist():
            upd = _read_npz(zf, "updaterState.npz")
            model.opt_states = _unflatten_into(model.opt_states, upd)
        _restore_loss_scale(zf, model)
        meta = json.loads(zf.read("meta.json").decode())
        model._iteration = meta.get("iteration", 0)
        model._epoch = meta.get("epoch", 0)
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path: str, load_updater: bool = True):
        """Reference: ModelSerializer.restoreMultiLayerNetwork."""
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path) as zf:
            conf = MultiLayerConfiguration.from_json(
                zf.read("configuration.json").decode())
            model = MultiLayerNetwork(conf).init()
            return ModelSerializer._load_members(model, zf, load_updater)

    @staticmethod
    def restoreComputationGraph(path: str, load_updater: bool = True):
        """Reference: ModelSerializer.restoreComputationGraph."""
        from deeplearning4j_tpu.nn.graph.config import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

        with zipfile.ZipFile(path) as zf:
            conf = ComputationGraphConfiguration.from_json(
                zf.read("configuration.json").decode())
            model = ComputationGraph(conf).init()
            return ModelSerializer._load_members(model, zf, load_updater)

    @staticmethod
    def restore(path: str, load_updater: bool = True):
        """Dispatch on the saved model_type (meta.json)."""
        with zipfile.ZipFile(path) as zf:
            meta = json.loads(zf.read("meta.json").decode())
        if meta.get("model_type") == "ComputationGraph":
            return ModelSerializer.restoreComputationGraph(path, load_updater)
        return ModelSerializer.restoreMultiLayerNetwork(path, load_updater)

    @staticmethod
    def restoreNormalizer(path: str):
        from deeplearning4j_tpu.datasets.normalizers import (
            CompositeDataSetPreProcessor,
        )

        registry = _normalizer_registry()
        with zipfile.ZipFile(path) as zf:
            if "normalizer.json" not in zf.namelist():
                return None
            info = json.loads(zf.read("normalizer.json").decode())
            state = _read_npz(zf, "normalizer.npz")
            if info["type"] == "CompositeDataSetPreProcessor":
                # saves from before the save-time child validation may
                # carry children we cannot rebuild — fail with the
                # actual problem, not a KeyError deep in the registry
                bad = [t for t in info["children"] if t not in registry]
                if bad:
                    raise ValueError(
                        "cannot restore CompositeDataSetPreProcessor: "
                        f"children {bad} are not restorable normalizer "
                        f"types (expected one of {sorted(registry)})")
                n = CompositeDataSetPreProcessor(
                    *[registry[t]() for t in info["children"]])
                # _flatten_with_paths joined the per-child dicts as
                # "p<i>/<key>" — rebuild the nesting load expects
                nested: dict = {f"p{i}": {}
                                for i in range(len(info["children"]))}
                for k, v in state.items():
                    head, rest = k.split("/", 1)
                    nested[head][rest] = v
                n.load_state_dict(nested)
                return n
            if info["type"] not in registry:
                raise ValueError(
                    f"cannot restore normalizer of type {info['type']!r} "
                    f"(expected one of {sorted(registry)})")
            n = registry[info["type"]]()
            n.load_state_dict(state)
            return n
