"""Utilities: model serialization, crash reporting."""

from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util.sharded_checkpoint import ShardedCheckpoint

__all__ = ["ModelSerializer", "ShardedCheckpoint"]
