"""Utilities: model serialization, crash reporting, fault tolerance."""

from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util.sharded_checkpoint import (
    ShardedCheckpoint, model_checkpoint_tree, restore_model, save_model,
)

_RESILIENCE_EXPORTS = ("FaultTolerance", "DivergenceError", "StepWatchdog")


def __getattr__(name):
    # lazy (PEP 562): resilience documents that a fit WITHOUT a
    # FaultTolerance never imports it — importing it eagerly here would
    # make every `deeplearning4j_tpu.util` user (e.g. plain
    # ModelSerializer callers) pay its import and void that guarantee
    if name in _RESILIENCE_EXPORTS:
        from deeplearning4j_tpu.util import resilience

        return getattr(resilience, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


__all__ = ["ModelSerializer", "ShardedCheckpoint",
           "model_checkpoint_tree", "save_model", "restore_model",
           "FaultTolerance", "DivergenceError", "StepWatchdog"]
