"""Utilities: model serialization, crash reporting."""

from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util.sharded_checkpoint import (
    ShardedCheckpoint, model_checkpoint_tree, restore_model, save_model,
)

__all__ = ["ModelSerializer", "ShardedCheckpoint",
           "model_checkpoint_tree", "save_model", "restore_model"]
