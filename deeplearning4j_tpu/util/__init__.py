"""Utilities: model serialization, crash reporting."""

from deeplearning4j_tpu.util.model_serializer import ModelSerializer

__all__ = ["ModelSerializer"]
