"""Memory workspaces, device memory stats, crash reporting.

Reference (SURVEY.md §2.10/§2.11/§5):
- org/nd4j/linalg/api/memory/** — MemoryWorkspace arena allocator with
  WorkspaceConfiguration policies; LayerWorkspaceMgr scoping per layer.
- CUDA JITA AtomicAllocator device caches.
- org/deeplearning4j/util/CrashReportingUtil — full memory/config dump
  on OOM.

TPU redesign — what exists and what deliberately doesn't:
- The reference's arenas exist because every op allocates eagerly on
  the JVM heap + device. Under jit, XLA's buffer assignment plans ALL
  intermediate memory at compile time and donation recycles input
  buffers — the arena's job is done by the compiler. So MemoryWorkspace
  here is a SCOPING/ACCOUNTING tool (live scope tracking, device-memory
  deltas, leak assertions for tests), not an allocator.
- AtomicAllocator's host<->device coherency machinery is jax.Array's
  job; `device_memory_stats()` exposes what the reference's
  MemoryTracker reported.
- CrashReportingUtil survives nearly unchanged: dump model config,
  param counts, memory stats, workspace state on OOM.
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
import os
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np


# ------------------------------------------------------------- stats
def device_memory_stats(device=None) -> Dict[str, Any]:
    """Per-device memory stats (reference: MemoryTracker / JITA device
    cache counters). Empty dict when the backend doesn't report."""
    d = device or jax.local_devices()[0]
    try:
        ms = d.memory_stats() or {}
    except Exception:
        ms = {}
    return {
        "device": str(d),
        "platform": d.platform,
        "bytes_in_use": ms.get("bytes_in_use"),
        "peak_bytes_in_use": ms.get("peak_bytes_in_use"),
        "bytes_limit": ms.get("bytes_limit"),
    }


def host_memory_stats() -> Dict[str, Any]:
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return {"max_rss_mb": ru.ru_maxrss / 1024.0}
    except Exception:
        return {}


# --------------------------------------------------------- workspaces
class DebugMode(enum.Enum):
    DISABLED = "disabled"
    SPILL_EVERYTHING = "spill_everything"   # kept for API parity
    VALIDATE_SCOPES = "validate_scopes"


@dataclasses.dataclass
class WorkspaceConfiguration:
    """Mirror of the reference's builder fields. Allocation policies are
    recorded (and serialized with configs) but do not drive an
    allocator — XLA buffer assignment owns memory planning under jit."""

    initial_size: int = 0
    max_size: int = 0
    policy_allocation: str = "OVERALLOCATE"
    policy_learning: str = "FIRST_LOOP"
    policy_spill: str = "REALLOCATE"
    debug_mode: DebugMode = DebugMode.DISABLED


class MemoryWorkspace:
    """Scoped accounting region (context manager).

    Tracks scope nesting, tagged arrays, and device-memory delta across
    the scope — the observability half of the reference workspace,
    minus the arena (see module docstring).
    """

    def __init__(self, config: Optional[WorkspaceConfiguration] = None,
                 workspace_id: str = "WS"):
        self.config = config or WorkspaceConfiguration()
        self.id = workspace_id
        self._tracked: List[Any] = []
        self._mem_before: Optional[int] = None
        self.bytes_delta: Optional[int] = None

    # -- scope protocol (reference: notifyScopeEntered/Left) -----------
    def __enter__(self) -> "MemoryWorkspace":
        _WorkspaceManager.instance()._push(self)
        self._mem_before = device_memory_stats().get("bytes_in_use")
        return self

    def __exit__(self, exc_type, exc, tb):
        after = device_memory_stats().get("bytes_in_use")
        if self._mem_before is not None and after is not None:
            self.bytes_delta = after - self._mem_before
        _WorkspaceManager.instance()._pop(self)
        return False

    def track(self, arr) -> Any:
        """Tag an array as belonging to this scope (reference: arrays
        allocated inside the workspace). `leverage` detaches."""
        self._tracked.append(arr)
        return arr

    def leverage(self, arr) -> Any:
        if arr in self._tracked:
            self._tracked.remove(arr)
        return arr

    def tracked_count(self) -> int:
        return len(self._tracked)


class _WorkspaceManager:
    _inst: Optional["_WorkspaceManager"] = None

    def __init__(self):
        self._local = threading.local()

    @classmethod
    def instance(cls) -> "_WorkspaceManager":
        if cls._inst is None:
            cls._inst = _WorkspaceManager()
        return cls._inst

    def _stack(self) -> List[MemoryWorkspace]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def _push(self, ws: MemoryWorkspace) -> None:
        self._stack().append(ws)

    def _pop(self, ws: MemoryWorkspace) -> None:
        stack = self._stack()
        if not stack or stack[-1] is not ws:
            raise RuntimeError(
                f"workspace scope mismatch: closing {ws.id} but stack is "
                f"{[w.id for w in stack]}")
        stack.pop()

    def open_workspaces(self) -> List[str]:
        return [w.id for w in self._stack()]


def getWorkspaceManager() -> _WorkspaceManager:
    return _WorkspaceManager.instance()


def assert_no_workspaces_open(msg: str = "") -> None:
    """Reference: WorkspaceUtils.assertNoWorkspacesOpen — test/debug
    guard against leaked scopes."""
    open_ws = _WorkspaceManager.instance().open_workspaces()
    if open_ws:
        raise RuntimeError(
            f"Workspaces still open: {open_ws}. {msg}".strip())


# ----------------------------------------------------- crash reporting
class CrashReportingUtil:
    """Reference: org/deeplearning4j/util/CrashReportingUtil — dump a
    full memory/config report when training OOMs."""

    @staticmethod
    def generate_report(model=None, extra: Optional[dict] = None) -> str:
        lines = [
            "==== DL4J-TPU crash / memory report ====",
            f"time: {datetime.datetime.now().isoformat()}",
            f"jax backend: {jax.default_backend()} "
            f"({jax.device_count()} devices)",
        ]
        for d in jax.local_devices():
            lines.append(f"device memory: {device_memory_stats(d)}")
        lines.append(f"host memory: {host_memory_stats()}")
        lines.append("open workspaces: "
                     f"{_WorkspaceManager.instance().open_workspaces()}")
        if model is not None:
            try:
                lines.append(f"model: {type(model).__name__}, params="
                             f"{model.numParams():,}")
            except Exception:
                pass
            conf = getattr(model, "conf", None)
            if conf is not None and hasattr(conf, "to_json"):
                lines.append("config:")
                lines.append(conf.to_json())
        for k, v in (extra or {}).items():
            lines.append(f"{k}: {v}")
        return "\n".join(lines)

    @staticmethod
    def writeMemoryCrashDump(model=None, path: Optional[str] = None,
                             extra: Optional[dict] = None) -> str:
        path = path or os.path.join(
            os.getcwd(),
            f"dl4j-tpu-crash-{datetime.datetime.now():%Y%m%d-%H%M%S}.txt")
        with open(path, "w") as f:
            f.write(CrashReportingUtil.generate_report(model, extra))
        return path

    @staticmethod
    def wrap_oom(fn, model=None, dump_dir: Optional[str] = None):
        """Wrap a train/step callable: on XLA RESOURCE_EXHAUSTED (or
        host MemoryError), write the crash dump and re-raise."""

        def guarded(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except (MemoryError, Exception) as e:  # XlaRuntimeError subclass
                name = type(e).__name__
                msg = str(e)
                if isinstance(e, MemoryError) or \
                        "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg:
                    path = None
                    if dump_dir:
                        path = os.path.join(dump_dir, "oom-dump.txt")
                    written = CrashReportingUtil.writeMemoryCrashDump(
                        model, path, extra={"exception": f"{name}: {msg}"})
                    raise type(e)(
                        f"{msg}\n[crash dump written: {written}]") from e
                raise

        return guarded


__all__ = ["MemoryWorkspace", "WorkspaceConfiguration", "DebugMode",
           "getWorkspaceManager", "assert_no_workspaces_open",
           "device_memory_stats", "host_memory_stats",
           "CrashReportingUtil"]
