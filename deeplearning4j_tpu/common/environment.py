"""Global environment/flag singleton.

Reference: libnd4j ``sd::Environment`` (verbose/debug flags mirrored
across JNI), ``org/nd4j/config/ND4JSystemProperties`` /
``ND4JEnvironmentVars`` (env-var configuration), and
``Nd4jEnvironment.getEnvironmentInformation()`` (runtime/hardware
report used by PerformanceListener) — SURVEY.md §5 config/flag system.

Env vars (the DL4J_TPU_* namespace replaces ND4J_*):
- ``DL4J_TPU_PANIC=nan|inf|any`` — default numerics panic mode; WIRED:
  OpProfiler reads it at first use, so training steps panic-check
  without any code change.
- ``DL4J_TPU_VERBOSE=1`` / ``DL4J_TPU_DEBUG=1`` — flag accessors for
  user code and listeners (``Environment.isVerbose()``); the framework
  core does not condition on them yet.
- ``DL4J_TPU_MAX_THREADS=N`` — exposed via ``Environment.maxThreads()``
  for host-side worker pools user code spins up; the bundled native
  codec sizes its own std::thread pool internally.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


class Environment:
    """Singleton (reference: sd::Environment::getInstance())."""

    _instance: Optional["Environment"] = None

    def __init__(self):
        self._verbose = os.environ.get("DL4J_TPU_VERBOSE", "0") == "1"
        self._debug = os.environ.get("DL4J_TPU_DEBUG", "0") == "1"
        self._panic = os.environ.get("DL4J_TPU_PANIC", "").lower() or None
        try:
            self._max_threads = int(
                os.environ.get("DL4J_TPU_MAX_THREADS", "0")) or None
        except ValueError:
            self._max_threads = None

    @classmethod
    def getInstance(cls) -> "Environment":
        if cls._instance is None:
            cls._instance = Environment()
        return cls._instance

    # -- flags (reference naming) --------------------------------------
    def isVerbose(self) -> bool:
        return self._verbose or self._debug

    def setVerbose(self, v: bool) -> None:
        self._verbose = bool(v)

    def isDebug(self) -> bool:
        return self._debug

    def setDebug(self, v: bool) -> None:
        self._debug = bool(v)

    def panicMode(self) -> Optional[str]:
        """'nan' | 'inf' | 'any' | None — default for profiler panic."""
        return self._panic

    def setPanicMode(self, mode: Optional[str]) -> None:
        self._panic = mode

    def maxThreads(self) -> int:
        if self._max_threads:
            return self._max_threads
        return os.cpu_count() or 1

    def setMaxThreads(self, n: int) -> None:
        self._max_threads = int(n)


class Nd4jEnvironment:
    """Runtime/hardware report (reference:
    org/nd4j/linalg/api/environment/Nd4jEnvironment — feeds
    PerformanceListener's system-info lines)."""

    @staticmethod
    def getEnvironmentInformation() -> Dict[str, Any]:
        import platform as _platform

        import jax

        devs = jax.devices()
        info: Dict[str, Any] = {
            "backend": devs[0].platform if devs else "none",
            "blas.vendor": "XLA",   # matmuls lower to the MXU, not BLAS
            "device.count": len(devs),
            "device.kind": devs[0].device_kind if devs else "none",
            "host.cpu.count": os.cpu_count(),
            "host.name": _platform.node(),
            "jax.version": jax.__version__,
            "os": f"{_platform.system()} {_platform.release()}",
            "python.version": _platform.python_version(),
        }
        try:
            stats = devs[0].memory_stats()
            if stats:
                info["device.memory.bytes.limit"] = stats.get(
                    "bytes_limit")
                info["device.memory.bytes.in.use"] = stats.get(
                    "bytes_in_use")
        except Exception:
            pass  # CPU backend has no memory_stats
        return info
