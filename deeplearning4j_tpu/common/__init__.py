"""Shared infrastructure: config serde, environment flags."""
