"""Config JSON serde (reference: Jackson round-trip on every config —
MultiLayerConfiguration#toJson/fromJson, updater/layer polymorphic
(de)serializers, SURVEY.md §2.18, §5 config system).

Every serializable config is a dataclass registered here; polymorphism
is encoded as {"@class": <registered name>, ...fields}, mirroring the
reference's Jackson type info. Round-trip is a hard API contract:
`from_json(to_json(cfg)) == cfg` for every config in the framework.
"""

from __future__ import annotations

import dataclasses
import enum as _enum
import json
from typing import Any, Dict, Type

_CLASSES: Dict[str, type] = {}


def serializable(cls=None):
    """Class decorator: register a dataclass for polymorphic JSON serde."""

    def wrap(c):
        if not dataclasses.is_dataclass(c):
            raise TypeError(f"@serializable requires a dataclass: {c}")
        _CLASSES[c.__name__] = c
        return c

    return wrap(cls) if cls is not None else wrap


def to_dict(obj: Any) -> Any:
    """Recursively convert registered dataclasses to tagged dicts."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        if name not in _CLASSES:
            # fail HERE with the class named, not deep inside
            # json.dumps (or worse, silently now and at from_json
            # later) — e.g. LambdaLayer holds a function and is
            # deliberately not serializable
            raise TypeError(
                f"{name} is not JSON-serializable (not @serializable-"
                "registered); networks containing it cannot round-trip "
                "to_json()")
        d = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = to_dict(getattr(obj, f.name))
        return d
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, _enum.Enum):
        # enums serialize by value (reference: Jackson enum handling);
        # configs resolve the value back (e.g. PoolingType("max"))
        return obj.value
    return obj


def from_dict(d: Any) -> Any:
    """Inverse of to_dict: rebuild registered dataclasses from tags."""
    if isinstance(d, dict):
        if "@class" in d:
            name = d["@class"]
            if name not in _CLASSES:
                raise KeyError(f"Unknown serialized class: {name}")
            cls = _CLASSES[name]
            kwargs = {k: from_dict(v) for k, v in d.items() if k != "@class"}
            field_names = {f.name for f in dataclasses.fields(cls)}
            # tolerate forward-compatible extra keys, like the reference's
            # legacy-format deserializers do
            kwargs = {k: v for k, v in kwargs.items() if k in field_names}
            obj = cls(**kwargs)
            return obj
        return {k: from_dict(v) for k, v in d.items()}
    if isinstance(d, list):
        return [from_dict(v) for v in d]
    return d


def to_json(obj: Any, indent: int | None = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))


def _tuplify(v):
    """JSON turns tuples into lists; configs that need tuples call this."""
    return tuple(v) if isinstance(v, list) else v
