"""Loss functions (reference: org/nd4j/linalg/lossfunctions/** —
LossFunctions.LossFunction enum + ILossFunction impls, SURVEY.md §2.17).

Contract mirrors the reference's ILossFunction: given (labels,
preOutput, activation, mask) produce per-example scores and the overall
mean; gradient flows through jax.grad rather than hand-written
computeGradient methods (the reference hand-derives each — here autodiff
is the engine, and correctness is checked against finite differences).

All fns: (labels, output) -> per-example loss [N]; `mask` optional
broadcastable weights. Reductions happen in the trainer.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _mean_per_example(loss, axis):
    """Reduce feature axes, keep example axis."""
    if loss.ndim <= 1:
        return loss
    return jnp.sum(loss, axis=axis)


def mse(labels, output):
    """Per-example sum of squared errors / n_outputs (reference: LossMSE)."""
    d = output - labels
    return jnp.mean(d * d, axis=tuple(range(1, output.ndim)))


def l2(labels, output):
    d = output - labels
    return jnp.sum(d * d, axis=tuple(range(1, output.ndim)))


def l1(labels, output):
    return jnp.sum(jnp.abs(output - labels), axis=tuple(range(1, output.ndim)))


def mae(labels, output):
    return jnp.mean(jnp.abs(output - labels), axis=tuple(range(1, output.ndim)))


def mcxent(labels, probs, eps=1e-7):
    """Multi-class cross-entropy on probabilities (post-softmax),
    matching reference LossMCXENT applied after softmax activation."""
    p = jnp.clip(probs, eps, 1.0)
    return -jnp.sum(labels * jnp.log(p), axis=tuple(range(1, probs.ndim)))


def softmax_xent_logits(labels, logits):
    """Fused, numerically-stable CE on logits — the path the compiled
    trainer actually uses when the output activation is softmax."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logp, axis=tuple(range(1, logits.ndim)))


def xent_binary(labels, probs, eps=1e-7):
    p = jnp.clip(probs, eps, 1 - eps)
    loss = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    return jnp.sum(loss, axis=tuple(range(1, probs.ndim)))


def sigmoid_xent_logits(labels, logits):
    loss = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(loss, axis=tuple(range(1, logits.ndim)))


def hinge(labels, output):
    """labels in {-1,1} (reference: LossHinge)."""
    return jnp.sum(jnp.maximum(0.0, 1.0 - labels * output),
                   axis=tuple(range(1, output.ndim)))


def squared_hinge(labels, output):
    return jnp.sum(jnp.maximum(0.0, 1.0 - labels * output) ** 2,
                   axis=tuple(range(1, output.ndim)))


def kl_divergence(labels, probs, eps=1e-7):
    p = jnp.clip(probs, eps, 1.0)
    l = jnp.clip(labels, eps, 1.0)
    return jnp.sum(labels * (jnp.log(l) - jnp.log(p)),
                   axis=tuple(range(1, probs.ndim)))


def poisson(labels, output, eps=1e-7):
    return jnp.sum(output - labels * jnp.log(output + eps),
                   axis=tuple(range(1, output.ndim)))


def cosine_proximity(labels, output, eps=1e-8):
    ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + eps)
    on = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + eps)
    return -jnp.sum(ln * on, axis=tuple(range(1, output.ndim)))


def huber(labels, output, delta=1.0):
    d = jnp.abs(output - labels)
    quad = 0.5 * d * d
    lin = delta * d - 0.5 * delta * delta
    return jnp.sum(jnp.where(d <= delta, quad, lin),
                   axis=tuple(range(1, output.ndim)))


def mape(labels, output, eps=1e-7):
    return jnp.mean(100.0 * jnp.abs((labels - output) / (jnp.abs(labels) + eps)),
                    axis=tuple(range(1, output.ndim)))


def msle(labels, output, eps=1e-7):
    d = jnp.log1p(jnp.maximum(output, -1 + eps)) - jnp.log1p(jnp.maximum(labels, -1 + eps))
    return jnp.mean(d * d, axis=tuple(range(1, output.ndim)))


def sparse_mcxent(labels, logits):
    """Integer labels variant (reference: LossSparseMCXENT)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]


# Keras/common spellings users reach for first (the reference accepts
# these through its Keras-import layer; accept them everywhere)
_LOSS_ALIASES = {
    "categorical_crossentropy": "MCXENT",
    "sparse_categorical_crossentropy": "SPARSE_MCXENT",
    "binary_crossentropy": "XENT",
    "mean_squared_error": "MSE",
    "mean_absolute_error": "MAE",
    "kld": "KL_DIVERGENCE",
    "kullback_leibler_divergence": "KL_DIVERGENCE",
    "nll": "NEGATIVELOGLIKELIHOOD",
}


def wasserstein(labels, output):
    """Reference: LossWasserstein — mean(labels * preOutput); labels
    are the critic's +1/-1 (real/fake) signs in WGAN training."""
    return jnp.mean(labels * output, axis=tuple(range(1, output.ndim)))


def reconstruction_crossentropy(labels, output):
    """Reference: LossReconstructionCrossEntropy (pretrain
    autoencoders) — binary CE over activated outputs with the
    reference's wider 1e-5 epsilon clamp."""
    return xent_binary(labels, output, eps=1e-5)


class LossFunction(enum.Enum):
    """Reference: LossFunctions.LossFunction enum names."""

    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    XENT = "xent"                 # binary cross entropy
    MCXENT = "mcxent"             # multi-class cross entropy
    SPARSE_MCXENT = "sparse_mcxent"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    COSINE_PROXIMITY = "cosine_proximity"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"  # alias of MCXENT
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mape"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "msle"
    HUBER = "huber"
    WASSERSTEIN = "wasserstein"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"

    @property
    def fn(self) -> Callable:
        return {
            LossFunction.MSE: mse,
            LossFunction.L1: l1,
            LossFunction.L2: l2,
            LossFunction.MAE: mae,
            LossFunction.XENT: xent_binary,
            LossFunction.MCXENT: mcxent,
            LossFunction.SPARSE_MCXENT: sparse_mcxent,
            LossFunction.KL_DIVERGENCE: kl_divergence,
            LossFunction.POISSON: poisson,
            LossFunction.HINGE: hinge,
            LossFunction.SQUARED_HINGE: squared_hinge,
            LossFunction.COSINE_PROXIMITY: cosine_proximity,
            LossFunction.NEGATIVELOGLIKELIHOOD: mcxent,
            LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR: mape,
            LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR: msle,
            LossFunction.HUBER: huber,
            LossFunction.WASSERSTEIN: wasserstein,
            LossFunction.RECONSTRUCTION_CROSSENTROPY:
                reconstruction_crossentropy,
        }[self]

    @staticmethod
    def resolve(l) -> "LossFunction":
        if isinstance(l, LossFunction):
            return l
        if isinstance(l, str):
            key = _LOSS_ALIASES.get(l.lower(), l)
            if key.upper() in LossFunction.__members__:
                return LossFunction[key.upper()]
            try:
                return LossFunction(key.lower())
            except ValueError:
                raise ValueError(
                    f"Unknown loss {l!r}; valid: "
                    f"{sorted(LossFunction.__members__)}") from None
        raise ValueError(f"Cannot resolve loss: {l!r}")


#: losses whose per-example value is a MEAN over feature axes (all
#: others SUM) — drives the masked divisor so all-ones mask == unmasked
_MEAN_REDUCED_LOSSES = frozenset({
    LossFunction.MSE, LossFunction.MAE, LossFunction.WASSERSTEIN,
    LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR,
    LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR,
})


def compute_loss(loss_fn: LossFunction, labels, preoutput, activation, mask=None):
    """Activation-aware loss on pre-activations, with the reference's
    fused special cases (softmax+MCXENT, sigmoid+XENT) for stability.

    mask semantics (reference: ILossFunction mask arg):
    - [N] or [N,1] per-example weights
    - per-timestep weights matching labels.shape[:-1] (or with a
      trailing 1) for [N, T, C] outputs — handled by folding time into
      the example axis, so every loss's per-example path applies per
      timestep.
    Normalization invariant: an all-ones mask produces EXACTLY the
    unmasked loss (masked entries contribute 0, the divisor stays what
    the unmasked reduction would use — minibatch N for sum-reduced
    losses, total element count for mean-reduced/sparse ones). This
    mirrors the reference's score/minibatch semantics.
    """
    from deeplearning4j_tpu.activations import Activation

    act = Activation.resolve(activation)
    n_examples = labels.shape[0]
    folded = False
    if mask is not None:
        if mask.ndim == labels.ndim and mask.shape[-1] == 1:
            mask = mask[..., 0]  # drop trailing singleton: [N,T,1]->[N,T]
        if mask.ndim >= 2 and mask.shape == labels.shape[:-1]:
            # per-timestep mask: [N,T,...] -> one "example" per timestep
            labels = labels.reshape(-1, labels.shape[-1])
            preoutput = preoutput.reshape(-1, preoutput.shape[-1])
            mask = mask.reshape(-1)
            folded = True
        elif mask.ndim == 2 and mask.shape[1] == 1:
            mask = mask[:, 0]  # [N,1] per-example weights
    if loss_fn in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD) \
            and act is Activation.SOFTMAX:
        per_ex = softmax_xent_logits(labels, preoutput)
    elif loss_fn is LossFunction.SPARSE_MCXENT and act is Activation.SOFTMAX:
        per_ex = sparse_mcxent(labels, preoutput)
    elif loss_fn is LossFunction.XENT and act is Activation.SIGMOID:
        per_ex = sigmoid_xent_logits(labels, preoutput)
    else:
        per_ex = loss_fn.fn(labels, act.fn(preoutput))
    if mask is not None:
        per_ex = per_ex * mask.reshape(per_ex.shape)
        # divisor reproduces the unmasked reduction (see docstring):
        # - sum-reduced losses fold T into the example axis but the
        #   unmasked path averaged over N only -> divide by N
        # - losses in _MEAN_REDUCED_LOSSES (MSE/MAE/MAPE/MSLE/
        #   Wasserstein) and elementwise
        #   sparse CE averaged over every entry -> divide by per_ex.size
        if folded and loss_fn not in _MEAN_REDUCED_LOSSES:
            divisor = n_examples
        else:
            divisor = per_ex.size
        return jnp.sum(per_ex) / divisor
    return jnp.mean(per_ex)
