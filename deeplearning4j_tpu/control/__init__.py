"""Elastic control plane: JobScheduler over one device fleet.

``JobScheduler`` runs many jobs — ``TrainJob`` (a supervised ``fit()``
with auto-resume, periodic bundles, stall verdicts, and checkpoint-and-
migrate across topology changes) and ``ServeJob`` (a ``ServingFleet``
with replica restart, traffic re-routing, and capacity hand-back) —
over a ``DeviceFleet`` of chips grouped into failure-domain workers.
See control/scheduler.py for the full story and docs/CONTROL_PLANE.md
for the operator guide.
"""

from deeplearning4j_tpu.control.scheduler import (
    TERMINAL, DeviceFleet, DeviceLostError, Job, JobContext,
    JobScheduler, ServeJob, TrainJob, default_scheduler,
    http_jobs_get, http_jobs_post, jobs_snapshot, set_default,
)

__all__ = ["JobScheduler", "TrainJob", "ServeJob", "Job", "JobContext",
           "DeviceFleet", "DeviceLostError", "TERMINAL",
           "set_default", "default_scheduler", "jobs_snapshot",
           "http_jobs_get", "http_jobs_post"]
