"""Elastic control plane: JobScheduler over one device fleet, and
(phase 2) workers as supervised OS processes.

``JobScheduler`` runs many jobs — ``TrainJob`` (a supervised ``fit()``
with auto-resume, periodic bundles, stall verdicts, priorities, and
checkpoint-and-migrate across topology changes) and ``ServeJob`` (a
``ServingFleet`` with replica restart, traffic re-routing, and
capacity hand-back) — over a ``DeviceFleet`` of chips grouped into
failure-domain workers. Phase 2 (control/worker.py) makes those
workers real OS processes under a ``WorkerSupervisor`` (heartbeat file
leases, preemption notices with deadlines, SIGKILL at the deadline,
task migration through a shared bundle store). See
control/scheduler.py + control/worker.py for the full story and
docs/CONTROL_PLANE.md for the operator guide.
"""

from deeplearning4j_tpu.control.scheduler import (
    TERMINAL, DeviceFleet, DeviceLostError, Job, JobContext,
    JobScheduler, ServeJob, TrainJob, default_scheduler,
    http_fleet_get, http_fleet_post, http_jobs_get, http_jobs_post,
    http_workers_get, http_workers_post,
    jobs_snapshot, set_default,
)

#: worker-process exports resolve LAZILY (PEP 562, like
#: profiler.slo): the supervisor-off contract is that a process which
#: never constructs a WorkerSupervisor never imports
#: control/worker.py — and both HTTP servers import this package on
#: every /v1/jobs request
_WORKER_EXPORTS = ("WorkerSupervisor", "WorkerTask",
                   "WorkerTaskContext", "default_supervisor",
                   "set_default_supervisor", "workers_snapshot")


def __getattr__(name):
    if name in _WORKER_EXPORTS or name == "worker":
        import importlib

        mod = importlib.import_module(
            "deeplearning4j_tpu.control.worker")
        return mod if name == "worker" else getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


__all__ = ["JobScheduler", "TrainJob", "ServeJob", "Job", "JobContext",
           "DeviceFleet", "DeviceLostError", "TERMINAL",
           "set_default", "default_scheduler", "jobs_snapshot",
           "http_fleet_get", "http_fleet_post",
           "http_jobs_get", "http_jobs_post",
           "http_workers_get", "http_workers_post",
           "WorkerSupervisor", "WorkerTask", "WorkerTaskContext",
           "default_supervisor", "set_default_supervisor",
           "workers_snapshot"]
