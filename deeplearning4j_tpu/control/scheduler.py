"""Elastic control plane: a preemption-tolerant JobScheduler that runs
many training and serving jobs over one unreliable device fleet.

Every survival mechanism this repo built exists in isolation — shard-
aware resume bundles that restore across topology changes
(util/resilience.py), NaN/divergence provenance (profiler/model_health),
flight-recorder incident dumps (profiler/flight_recorder.py), serving
replica kill/drain/restart with request replay (serving/fleet.py) —
but nothing composed them: kill a worker and the ``fit()`` just dies.
This module is the composition:

- **One device fleet, many jobs.** ``DeviceFleet`` owns the chips,
  grouped into *workers* (failure domains — the unit that preempts,
  hangs, or dies together). ``TrainJob``s gang-schedule ``chips``
  devices (a multi-chip zero job next to single-chip sweeps);
  ``ServeJob``s take one chip per serving replica.
- **Health verdicts.** The supervision loop classifies every failure
  signal the last six PRs produce: watchdog stalls (via the
  ``FaultTolerance.on_stall`` callback), divergence-budget aborts
  (``DivergenceError``, with NaN-layer provenance already on the
  incident dump), chaos-injected deaths (``WorkerKilledError``),
  device loss (``kill_worker``), and dead serving replicas.
- **Checkpoint and MIGRATE.** A killed train job recovers its newest
  digest-valid bundle and reschedules — on fewer chips when the fleet
  shrank — through the topology-change-safe restore path (an 8-way
  zero bundle restores on 4-way with bit-equal Adam moments, PR 6).
  ``FaultTolerance.checkpoint_every`` periodic bundles bound the loss
  to the last ``checkpoint_every`` steps even for SIGKILL-equivalent
  deaths that never get a grace period.
- **Serving re-route + rebalance.** A dead replica's traffic replays
  on survivors (the fleet already does this); the scheduler restarts
  the replica when its chip is healthy and shrinks the job when it is
  not. Capacity flows back through ``ServingFleet.capacity_listener``,
  and the ``queue_pressure()`` signal lets the scheduler drain an idle
  serving replica to feed a starved train job (rebalance).
- **Retry budgets.** Each restart consumes the job's ``max_retries``
  budget with exponential backoff (scheduler-initiated migrations are
  free — they are the scheduler's fault, not the job's).

Everything is observable: each transition lands in the flight recorder
(``job_*`` events; worker death is an *incident* — a full atomic dump),
the ``dl4j_tpu_jobs_*`` metrics cover states/devices/restarts/
migrations plus per-tenant throughput-MFU-latency gauges, and the
``/v1/jobs`` HTTP surface (ui/server.py + remote/server.py) serves
submit/status/drain/cancel.

Scheduler-off identity: nothing here is imported by the fit loops or
the serving engine — a process that never builds a ``JobScheduler``
runs the exact pre-control-plane code paths.
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.profiler import chaos as _chaos
from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")

_JOB_IDS = itertools.count()

#: terminal states — a job here never transitions again
TERMINAL = ("completed", "failed", "cancelled", "drained")


class DeviceLostError(RuntimeError):
    """The devices a job was running on left the fleet (worker death,
    platform preemption of a host). Retryable: the job migrates."""


def _count_preemption(kind: str, job_id: str) -> None:
    """dl4j_tpu_jobs_preemptions_total{kind=notice|priority}."""
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.JOBS_PREEMPTIONS,
            "job preemptions delivered by the control plane (cluster "
            "maintenance notice or priority eviction)").inc(
            kind=kind, job=job_id)


# ======================================================================
# device fleet
# ======================================================================
class DeviceFleet:
    """The scheduler's chip pool, grouped into workers (failure
    domains). On the CPU test topology the 8 virtual devices all live
    in one process, so ``workers=`` lets tests (and the chaos drill)
    define the failure domains explicitly; the default groups by
    ``device.process_index`` — the real multi-host boundary."""

    def __init__(self, devices: Optional[Sequence[Any]] = None,
                 workers: Optional[Dict[str, Sequence[Any]]] = None):
        if devices is None and workers is None:
            import jax

            devices = list(jax.devices())
        if workers is None:
            grouped: Dict[str, List[Any]] = {}
            for d in devices:
                grouped.setdefault(
                    f"w{getattr(d, 'process_index', 0)}", []).append(d)
            workers = grouped
        self._worker_of: Dict[Any, str] = {}
        self._workers: Dict[str, List[Any]] = {}
        for w, devs in workers.items():
            self._workers[str(w)] = list(devs)
            for d in devs:
                self._worker_of[d] = str(w)
        self._lock = threading.Lock()
        self._free: List[Any] = [d for devs in self._workers.values()
                                 for d in devs]
        self._used: Dict[Any, str] = {}       # device -> job_id
        self._lost: set = set()
        #: maintenance-noticed devices: still with their current
        #: owners while jobs drain, never handed out again until the
        #: worker is restored (or actually lost at the deadline)
        self._condemned: set = set()

    # ------------------------------------------------------- accounting
    @property
    def total(self) -> int:
        with self._lock:
            return len(self._free) + len(self._used)

    @property
    def free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def lost(self) -> int:
        with self._lock:
            return len(self._lost)

    def worker_of(self, device) -> Optional[str]:
        return self._worker_of.get(device)

    def workers(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for w, devs in self._workers.items():
                out[w] = {
                    "devices": len(devs),
                    "lost": sum(1 for d in devs if d in self._lost),
                    "used": sum(1 for d in devs if d in self._used),
                    "condemned": any(d in self._condemned
                                     for d in devs),
                }
            return out

    # ------------------------------------------------------- allocation
    def acquire(self, n: int, job_id: str) -> Optional[List[Any]]:
        """Gang allocation: ``n`` healthy devices or None (never a
        partial grant — a zero job on half its mesh is not a smaller
        job, it is a different one the caller must ask for)."""
        with self._lock:
            if n > len(self._free):
                return None
            devs = [self._free.pop() for _ in range(n)]
            for d in devs:
                self._used[d] = job_id
            return devs

    def acquire_device(self, device, job_id: str) -> bool:
        """Claim ONE SPECIFIC healthy device (the SLO-driven serve
        scale-up wants the exact chip a drained replica was built on —
        its engine config, warm-pool donor and page pools are bound to
        it). True when the device is now (or already was) assigned to
        ``job_id``."""
        with self._lock:
            if device in self._lost:
                return False
            if device in self._free:
                self._free.remove(device)
                self._used[device] = job_id
                return True
            return self._used.get(device) == job_id

    def release(self, devices: Sequence[Any]) -> None:
        """Return devices to the pool. Idempotent per device (a device
        already returned — or lost — is skipped): the fleet capacity
        listener and job teardown may both try to give a chip back.
        A CONDEMNED device (maintenance notice pending) is released
        but not re-offered — it waits out the notice."""
        with self._lock:
            for d in devices:
                if d in self._used and d not in self._lost:
                    del self._used[d]
                    if d not in self._condemned:
                        self._free.append(d)
                elif d in self._lost:
                    self._used.pop(d, None)

    def condemn_worker(self, worker: str) -> List[Any]:
        """Maintenance notice for a whole worker: its devices stay
        with their current owners while those jobs checkpoint-and-
        drain, but are never handed out again — a job migrating off
        the doomed worker must not land back on it. ``lose_worker``
        (at the deadline) or ``restore_worker`` (notice cancelled /
        host back) resolves the state."""
        devs = self._workers.get(str(worker), [])
        with self._lock:
            for d in devs:
                self._condemned.add(d)
                if d in self._free:
                    self._free.remove(d)
        return list(devs)

    def lose_worker(self, worker: str) -> List[Any]:
        """Remove a whole worker's devices from the fleet (death /
        preemption). Returns the devices that were lost; jobs holding
        them learn through the scheduler's verdict path."""
        devs = self._workers.get(str(worker), [])
        with self._lock:
            for d in devs:
                self._lost.add(d)
                self._condemned.discard(d)
                if d in self._free:
                    self._free.remove(d)
            return list(devs)

    def restore_worker(self, worker: str) -> List[Any]:
        """Bring a lost (or condemned) worker's devices back (the
        host rebooted / the maintenance window passed)."""
        devs = self._workers.get(str(worker), [])
        restored = []
        with self._lock:
            for d in devs:
                if d in self._lost or d in self._condemned:
                    self._lost.discard(d)
                    self._condemned.discard(d)
                    if d not in self._used and d not in self._free:
                        self._free.append(d)
                    restored.append(d)
        return restored

    def owner(self, device) -> Optional[str]:
        with self._lock:
            return self._used.get(device)

    def is_lost(self, device) -> bool:
        with self._lock:
            return device in self._lost

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"total": len(self._free) + len(self._used),
                    "free": len(self._free),
                    "used": len(self._used),
                    "lost": len(self._lost),
                    "condemned": len(self._condemned)}


# ======================================================================
# jobs
# ======================================================================
class JobContext:
    """What a job's build/run function receives: its device grant, the
    attempt ordinal, and (train) the scheduler-configured
    FaultTolerance policy it MUST pass to ``fit``."""

    def __init__(self, job: "Job", scheduler: "JobScheduler",
                 devices: List[Any], attempt: int,
                 fault_tolerance=None):
        self.job = job
        self.scheduler = scheduler
        self.devices = list(devices)
        self.attempt = int(attempt)
        self.fault_tolerance = fault_tolerance

    def mesh(self, num_model: int = 1):
        """('data','model') mesh over exactly this job's devices —
        how a multi-chip zero job builds its ShardedTrainer."""
        from deeplearning4j_tpu.parallel.mesh import build_mesh

        return build_mesh(num_data=len(self.devices) // num_model,
                          num_model=num_model, devices=self.devices)


class Job:
    """Base job record. Subclasses: ``TrainJob`` / ``ServeJob``."""

    kind = "job"

    def __init__(self, *, name: Optional[str] = None, chips: int = 1,
                 tenant: str = "default", max_retries: int = 3,
                 backoff_s: float = 0.25, min_chips: int = 1,
                 priority: int = 0):
        self.job_id = f"{self.kind}-{next(_JOB_IDS)}"
        self.name = name or self.job_id
        self.tenant = str(tenant)
        self.chips = int(chips)
        self.min_chips = max(int(min_chips), 1)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        #: higher wins. Scheduling is priority-then-FIFO, and a gang
        #: that cannot fit may checkpoint-PREEMPT (never kill) running
        #: train jobs of STRICTLY lower priority; the victim parks in
        #: a ``preempted`` state and resumes — bit-identically, from
        #: its own bundles — when capacity frees. All-default
        #: priorities (0) reproduce the PR 13 FIFO exactly.
        self.priority = int(priority)
        self.state = "pending"
        self.devices: List[Any] = []
        self.attempts = 0
        self.retries_used = 0
        self.migrations = 0
        self.error: Optional[str] = None
        self.result: Any = None
        self.history: collections.deque = collections.deque(maxlen=64)
        self.submitted_t = time.time()
        self._not_before = 0.0          # backoff gate (monotonic)
        self._pending_since = time.monotonic()
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        # scheduler-intent flags for a clean runner exit
        self._migrate_on_exit = False
        self._cancel_on_exit = False
        self._drain_on_exit = False
        self._park_on_exit = False     # priority preemption: park,
        self._parked_since = 0.0       # don't requeue
        self._stalled_at: Optional[float] = None
        self._stall_deadline: Optional[float] = None
        self._exit_reason: Optional[str] = None
        # set by a migration requeue so a shrunken relaunch doesn't
        # count the SAME logical migration a second time
        self._migration_counted = False
        # throughput window
        self._last_progress_v: Optional[float] = None
        self._last_progress_t: Optional[float] = None
        self.throughput: Optional[float] = None

    def transition(self, to: str, reason: str = "") -> None:
        frm, self.state = self.state, to
        self.history.append({"t": time.time(), "from": frm, "to": to,
                             "reason": reason})
        _flight.record("job_state", job=self.job_id, frm=frm, to=to,
                       reason=reason)

    def status(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "name": self.name,
            "kind": self.kind,
            "tenant": self.tenant,
            "state": self.state,
            "chips": self.chips,
            "priority": self.priority,
            "devices": [str(d) for d in self.devices],
            "attempts": self.attempts,
            "retries_used": self.retries_used,
            "max_retries": self.max_retries,
            "migrations": self.migrations,
            "error": self.error,
            "throughput": self.throughput,
            "submitted_t": self.submitted_t,
            "history": list(self.history)[-8:],
        }


class TrainJob(Job):
    """One ``fit()`` under the scheduler's supervision.

    ``run_fn(ctx)`` builds the model/trainer/data on ``ctx.devices``
    (``ctx.mesh()`` for multi-chip) and calls
    ``fit(..., fault_tolerance=ctx.fault_tolerance)`` — the policy is
    how the scheduler reaches into the run: preemption checkpoints for
    migration, periodic bundles for kill recovery, the stall callback
    for hung-step verdicts, fault injection for the chaos drill. A
    ``checkpoint_dir`` makes the job resumable across restarts; without
    one, every restart is from scratch.

    ``progress`` (optional): zero-arg callable returning the live
    iteration count (or a dict with ``iteration`` and optionally
    ``mfu``) — feeds the per-tenant throughput/MFU gauges.
    """

    kind = "train"

    def __init__(self, run_fn: Callable[[JobContext], Any], *,
                 checkpoint_dir: Optional[str] = None,
                 bundle_store=None,
                 fault_tolerance=None,
                 checkpoint_every: Optional[int] = 10,
                 step_deadline: Optional[float] = None,
                 compile_grace_s: float = 120.0,
                 stall_grace_s: float = 30.0,
                 shrink: bool = True,
                 progress: Optional[Callable[[], Any]] = None,
                 **kw):
        super().__init__(**kw)
        self.run_fn = run_fn
        self.checkpoint_dir = checkpoint_dir
        self.stall_grace_s = float(stall_grace_s)
        self.shrink = bool(shrink)
        self.progress = progress
        if fault_tolerance is None:
            from deeplearning4j_tpu.util.resilience import FaultTolerance

            fault_tolerance = FaultTolerance(
                checkpoint_dir=checkpoint_dir,
                bundle_store=bundle_store,
                checkpoint_every=checkpoint_every,
                step_deadline=step_deadline,
                compile_grace_s=compile_grace_s)
        elif checkpoint_dir and not fault_tolerance.checkpoint_dir:
            fault_tolerance.checkpoint_dir = checkpoint_dir
        self.fault_tolerance = fault_tolerance
        if self.checkpoint_dir is None:
            # a bundle store implies a checkpoint anchor (shared-fs
            # migration is the whole point of handing one to a job)
            self.checkpoint_dir = fault_tolerance.checkpoint_dir


class ServeJob(Job):
    """A ``ServingFleet`` under the scheduler's supervision: one chip
    per replica, traffic re-routed off dead replicas by the fleet
    itself, replicas restarted (healthy chip) or the job shrunk (lost
    chip) by the scheduler, capacity handed back on drain.

    ``build_fn(ctx)`` returns a **ServingFleet** built over
    ``ctx.devices`` (``devices=ctx.devices`` — one replica each); the
    scheduler starts it, installs the capacity listener, and serves
    ``submit``/``generate`` through ``job.fleet``."""

    kind = "serve"

    def __init__(self, build_fn: Callable[[JobContext], Any], *,
                 replicas: Optional[int] = None, **kw):
        if replicas is not None:
            kw.setdefault("chips", int(replicas))
        super().__init__(**kw)
        self.build_fn = build_fn
        self.fleet = None
        #: SLO scale-up in flight (one restart at a time per job)
        self._scaling = False
        #: replicas ADDED by alert-driven elasticity, newest last:
        #: ``(rid, device)`` pairs — what _maybe_scale_down removes
        #: once the pressure alert has stayed quiet, returning the
        #: chips that preempted training to get here
        self._elastic: List[Any] = []
        #: monotonic time of the last elastic transition — scale-down
        #: holds off until the alert has been quiet this long AFTER
        #: the grow (a fresh replica must get a chance to drain the
        #: queue before its removal is even considered)
        self._elastic_since = 0.0
        #: replicas added by an operator's ``POST /v1/fleet/scale``
        #: — same ``(rid, device)`` bookkeeping, but NOT subject to
        #: automatic scale-down (an explicit target sticks until the
        #: operator scales back)
        self._manual: List[Any] = []

    def submit(self, *a, **kw):
        if self.fleet is None:
            raise RuntimeError(f"job {self.job_id} is not running")
        return self.fleet.submit(*a, **kw)

    def generate(self, *a, **kw):
        if self.fleet is None:
            raise RuntimeError(f"job {self.job_id} is not running")
        return self.fleet.generate(*a, **kw)


# ======================================================================
# the scheduler
# ======================================================================
class JobScheduler:
    """Supervision loop over one ``DeviceFleet`` (module docstring).

    Parameters
    ----------
    devices / workers : the fleet (default: every jax device, one
        worker per process — see ``DeviceFleet``).
    rebalance : drain idle serving replicas to feed starved train jobs
        (queue-pressure signal). On by default; thresholds are
        conservative.
    rebalance_after_s : how long a train job must starve before a
        serving replica is considered for draining.
    rebalance_pressure : a fleet must be under this queue pressure to
        give up a replica.
    slo : an ``profiler.slo.SLOEngine`` to subscribe to (or call
        ``attach_slo`` later). With one attached, serve capacity flows
        BOTH ways with hysteresis instead of one-shot polls: a firing
        ``action="scale_serve"`` alert (sustained queue pressure)
        restarts a drained/dead replica for the matching ServeJob — or,
        when none exists, GROWS the fleet with a brand-new replica on a
        chip freed by checkpoint-preempting the lowest-priority train
        job (``_scale_up_serve``); once the alert has stayed quiet for
        ``scale_down_hold_s`` the elastic replica drains back out and
        the parked job resumes bit-identically (``_maybe_scale_down``).
    scale_down_hold_s : how long the queue-pressure alert must stay
        resolved/inactive before an elastic replica is removed — the
        shrink-side hysteresis on top of the alert's own flap
        suppression.
    poll_s : supervision loop cadence.
    """

    def __init__(self, devices=None, workers=None, *,
                 rebalance: bool = True,
                 rebalance_after_s: float = 5.0,
                 rebalance_pressure: float = 0.05,
                 scale_down_hold_s: float = 10.0,
                 slo=None,
                 supervisor=None,
                 poll_s: float = 0.05,
                 flight_dir: Optional[str] = None,
                 make_default: bool = True):
        self.devices = DeviceFleet(devices, workers)
        self.rebalance = bool(rebalance)
        self.rebalance_after_s = float(rebalance_after_s)
        self.rebalance_pressure = float(rebalance_pressure)
        self.scale_down_hold_s = float(scale_down_hold_s)
        self.poll_s = float(poll_s)
        self.flight_dir = flight_dir
        self._slo = None
        self._supervisor = None
        self._jobs: "collections.OrderedDict[str, Job]" = \
            collections.OrderedDict()
        self._queue: collections.deque = collections.deque()
        self._factories: Dict[str, Callable[..., Job]] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._preempt_timers: Dict[str, threading.Timer] = {}
        self._thread: Optional[threading.Thread] = None
        self._last_gauges = 0.0
        self._last_slo_reconcile = 0.0
        if make_default:
            set_default(self)
        if slo is not None:
            self.attach_slo(slo)
        if supervisor is not None:
            self.attach_supervisor(supervisor)

    def attach_supervisor(self, supervisor) -> None:
        """Wire a ``WorkerSupervisor`` (control/worker.py) into the
        verdict path: a dead worker PROCESS maps onto
        ``lose_worker`` + device-loss migration exactly like a chaos
        ``kill_worker``, and a respawned worker's first heartbeat
        restores its devices to the fleet. Worker names must match
        the fleet's failure domains for the mapping to bite; unknown
        names are supervisor-local only."""
        self._supervisor = supervisor
        if getattr(supervisor, "scheduler", None) is not self:
            supervisor.scheduler = self

    # ------------------------------------------ supervisor verdict hooks
    def on_worker_process_dead(self, worker: str,
                               why: str = "") -> None:
        """Supervisor hook: a worker process exited or its heartbeat
        lease expired — a real OS-level death, mapped onto the
        existing recover-newest-bundle-and-migrate path."""
        worker = str(worker)
        devs = self.devices._workers.get(worker)
        if not devs:
            return              # not a fleet failure domain
        with self.devices._lock:
            if all(d in self.devices._lost for d in devs):
                return          # already handled (kill_worker drill)
        self._worker_lost(worker, why=f"process death: {why}")

    def on_worker_process_alive(self, worker: str) -> None:
        """Supervisor hook: a respawned worker heartbeats again — its
        devices rejoin the fleet as restore_worker capacity."""
        if str(worker) in self.devices._workers:
            self.restore_worker(worker)

    def attach_slo(self, engine) -> None:
        """Subscribe to an SLOEngine's alert transitions: sustained
        queue-pressure alerts (``action="scale_serve"``) drive serve-
        replica scale-up, and their pending/firing state vetoes
        rebalance drains (hysteresis — see _maybe_rebalance)."""
        self._slo = engine
        engine.on_alert(self._on_slo_alert,
                        states=("firing", "resolved"))

    # ------------------------------------------------------- lifecycle
    def start(self) -> "JobScheduler":
        with self._lock:
            if self._thread is not None:
                return self
            if self._stop.is_set():
                raise RuntimeError("scheduler has been shut down")
            _flight.install_excepthook()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="JobScheduler")
            self._thread.start()
        return self

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop supervising: cancel pending jobs, preempt running train
        jobs (they checkpoint and exit), shut down serving fleets, join
        every runner thread."""
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.state not in TERMINAL:
                try:
                    self.cancel(job.job_id)
                except Exception:
                    pass
        deadline = time.monotonic() + timeout
        for job in jobs:
            t = job._thread
            if t is not None and t.is_alive():
                t.join(max(0.0, deadline - time.monotonic()))
        self._stop.set()
        self._wake.set()
        for timer in list(self._preempt_timers.values()):
            timer.cancel()
        self._preempt_timers.clear()
        t = self._thread
        if t is not None:
            t.join(max(1.0, deadline - time.monotonic()))
        # one last reap so cancelled jobs reach a terminal state even
        # though the loop is gone
        self._poll_jobs()
        if default_scheduler() is self:
            set_default(None)

    def __enter__(self) -> "JobScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---------------------------------------------------------- client
    def submit(self, job: Job) -> Job:
        if self._stop.is_set():
            raise RuntimeError("scheduler has been shut down")
        with self._lock:
            self._jobs[job.job_id] = job
            self._queue.append(job.job_id)
            job._pending_since = time.monotonic()
        _flight.record("job_submit", job=job.job_id, job_kind=job.kind,
                       name=job.name, tenant=job.tenant,
                       chips=job.chips)
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.JOBS_SUBMITTED,
                "jobs submitted to the scheduler").inc(
                kind=job.kind, tenant=job.tenant)
        self.start()
        self._wake.set()
        return job

    def register_factory(self, name: str,
                         fn: Callable[..., Job]) -> None:
        """Named job factory for the HTTP submit surface: POST
        /v1/jobs {"factory": name, "params": {...}} builds the job
        here — callables don't travel over JSON."""
        self._factories[str(name)] = fn

    def submit_factory(self, name: str, **params) -> Job:
        fn = self._factories.get(str(name))
        if fn is None:
            raise KeyError(
                f"unknown job factory {name!r} (registered: "
                f"{sorted(self._factories)})")
        return self.submit(fn(**params))

    def job(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs[job_id]

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def wait(self, job_id: str, timeout: Optional[float] = None,
             states: Sequence[str] = TERMINAL) -> Job:
        """Block until the job reaches one of ``states`` (terminal by
        default). Raises TimeoutError otherwise."""
        job = self.job(job_id)
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while job.state not in states:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"job {job_id} still {job.state} after {timeout}s")
            time.sleep(0.02)
        return job

    def cancel(self, job_id: str) -> Job:
        """Cancel a job: pending jobs leave the queue; a running train
        job checkpoints (preemption path) and exits; a serving job
        cancels its in-flight requests (``FleetRequest.cancel``) and
        shuts its fleet down."""
        job = self.job(job_id)
        with self._lock:
            if job.state in TERMINAL:
                return job
            if job.state in ("pending", "restarting", "preempted"):
                # parked (priority-preempted) jobs have no runner
                # thread: cancelling is pure bookkeeping
                try:
                    self._queue.remove(job_id)
                except ValueError:
                    pass
                self._finish(job, "cancelled", reason="cancel")
                return job
        _flight.record("job_cancel", job=job.job_id)
        if isinstance(job, TrainJob):
            job._cancel_on_exit = True
            job.fault_tolerance.request_preemption()
        elif isinstance(job, ServeJob):
            job._cancel_on_exit = True
            self._teardown_fleet(job, cancel_requests=True)
        self._wake.set()
        return job

    def drain(self, job_id: str,
              timeout: Optional[float] = 60.0) -> Job:
        """Graceful stop: a train job checkpoints and exits (resumable
        later from its bundles); a serving job finishes its queued and
        in-flight requests, then shuts down. Devices return to the
        pool either way."""
        job = self.job(job_id)
        with self._lock:
            if job.state in TERMINAL:
                return job
            if job.state in ("pending", "restarting", "preempted"):
                try:
                    self._queue.remove(job_id)
                except ValueError:
                    pass
                self._finish(job, "drained", reason="drain")
                return job
        _flight.record("job_drain", job=job.job_id)
        job.transition("draining", "drain requested")
        if isinstance(job, TrainJob):
            job._drain_on_exit = True
            job.fault_tolerance.request_preemption()
        elif isinstance(job, ServeJob):
            job._drain_on_exit = True
            t = threading.Thread(
                target=self._drain_serve, args=(job, timeout),
                daemon=True, name=f"JobRunner-drain-{job.job_id}")
            job._thread = t
            t.start()
        self._wake.set()
        return job

    # ------------------------------------------------------ chaos drill
    def kill_worker(self, worker: str) -> List[Any]:
        """The chaos drill: a whole worker (failure domain) dies.
        Its devices leave the fleet; train jobs on them die
        SIGKILL-equivalently (no checkpoint — ``inject_fault``) and
        migrate onto what remains; serving replicas on them die and
        their traffic replays on survivors. Emits a flight-recorder
        INCIDENT dump — a worker death is exactly the post-mortem the
        black box exists for. With a supervisor attached and the name
        supervised, the worker PROCESS is SIGKILLed too — the drill
        is then a real OS-level death."""
        sup = self._supervisor
        if sup is not None and str(worker) in getattr(sup, "_handles",
                                                      {}):
            try:
                sup.kill(worker)
            except Exception:
                log.exception("control: supervisor kill(%s) failed",
                              worker)
        return self._worker_lost(worker, why="chaos kill_worker")

    def _worker_lost(self, worker: str, why: str = "") -> List[Any]:
        """Shared death path for kill_worker, the supervisor's
        process-death hook, and a missed preemption deadline."""
        timer = self._preempt_timers.pop(str(worker), None)
        if timer is not None:
            # the worker died before its maintenance deadline: the
            # pending timer must not replay this loss as a second
            # incident at the deadline
            timer.cancel()
        devs = self.devices.lose_worker(worker)
        affected: List[str] = []
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.state in TERMINAL or not job.devices:
                continue
            hit = [d for d in job.devices if d in devs]
            if not hit:
                continue
            affected.append(job.job_id)
            if isinstance(job, TrainJob):
                job.fault_tolerance.inject_fault(DeviceLostError(
                    f"worker {worker} lost ({len(hit)} of "
                    f"{len(job.devices)} devices)"))
            elif isinstance(job, ServeJob) and job.fleet is not None:
                for r in job.fleet._replicas:
                    if r.alive and r.engine._device in devs:
                        job.fleet.kill_replica(
                            r.index, DeviceLostError(
                                f"worker {worker} lost"))
        _flight.incident("job_worker_lost", directory=self.flight_dir,
                         worker=str(worker), why=why,
                         devices=[str(d) for d in devs],
                         jobs=affected)
        log.warning("control: worker %s lost (%s; %d devices, %d jobs "
                    "affected) — migrating", worker, why or "?",
                    len(devs), len(affected))
        self._wake.set()
        return devs

    # ------------------------------------------------ preemption notices
    def preempt_worker(self, worker: str,
                       deadline_s: float = 30.0) -> List[str]:
        """Cluster maintenance notice for a whole worker (the GCE/
        Borg-style event, also reachable as ``POST
        /v1/workers/<w>/preempt``): jobs on it checkpoint-and-drain
        BEFORE the kill instead of recovering after it. The worker's
        devices are CONDEMNED immediately (drains migrate onto other
        capacity, never back onto the doomed worker); at the deadline
        whatever is still running there dies for real and recovery
        degrades to the periodic-bundle story. Each affected drain
        counts one logical migration, not a retry — the platform's
        fault, not the job's. Returns the affected job ids."""
        worker = str(worker)
        if worker not in self.devices._workers:
            raise KeyError(f"unknown worker {worker!r} (have: "
                           f"{sorted(self.devices._workers)})")
        devs = set(self.devices.condemn_worker(worker))
        _flight.record("worker_preempt_notice", worker=worker,
                       deadline_s=deadline_s)
        affected: List[str] = []
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.state in TERMINAL or not job.devices:
                continue
            hit = [d for d in job.devices if d in devs]
            if not hit:
                continue
            affected.append(job.job_id)
            _count_preemption("notice", job.job_id)
            if isinstance(job, TrainJob):
                job._migrate_on_exit = True
                job._exit_reason = "preempt_notice"
                job.fault_tolerance.request_preemption(
                    deadline_s=deadline_s, kind="notice")
            elif isinstance(job, ServeJob) and job.fleet is not None:
                for r in job.fleet._replicas:
                    if r.alive and not r.draining \
                            and r.engine._device in devs:
                        r.draining = True
                        threading.Thread(
                            target=job.fleet.drain_replica,
                            args=(r.index,), daemon=True,
                            name=f"JobRunner-drain-{job.job_id}"
                        ).start()
        sup = self._supervisor
        if sup is not None and worker in getattr(sup, "_handles", {}):
            try:
                sup.preempt(worker, deadline_s=deadline_s)
            except Exception:
                log.exception("control: supervisor preempt(%s) failed",
                              worker)
        timer = threading.Timer(float(deadline_s),
                                self._complete_worker_preemption,
                                args=(worker,))
        timer.daemon = True
        timer.name = f"JobRunner-preempt-{worker}"
        prev = self._preempt_timers.pop(worker, None)
        if prev is not None:
            prev.cancel()
        self._preempt_timers[worker] = timer
        timer.start()
        log.warning("control: maintenance notice for worker %s — %d "
                    "job(s) draining, kill in %.1fs", worker,
                    len(affected), deadline_s)
        self._wake.set()
        return affected

    def _complete_worker_preemption(self, worker: str) -> None:
        """The notice deadline: the platform takes the worker NOW.
        Jobs that drained in time already migrated; anything still
        holding the worker's devices dies SIGKILL-equivalently and
        recovers from its newest periodic bundle."""
        self._preempt_timers.pop(str(worker), None)
        if self._stop.is_set():
            return
        _flight.record("worker_preempt_deadline", worker=str(worker))
        self._worker_lost(worker, why="preemption deadline expired")

    def restore_worker(self, worker: str) -> List[Any]:
        """A lost/condemned worker's capacity rejoins the fleet (host
        rebooted, maintenance window passed, supervisor respawned the
        process)."""
        timer = self._preempt_timers.pop(str(worker), None)
        if timer is not None:
            timer.cancel()       # the maintenance notice was lifted
        restored = self.devices.restore_worker(worker)
        if restored:
            _flight.record("job_worker_restored", worker=str(worker),
                           devices=[str(d) for d in restored])
            log.warning("control: worker %s restored (%d devices back "
                        "in the pool)", worker, len(restored))
        self._wake.set()
        return restored

    # ----------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            jobs = [j.status() for j in self._jobs.values()]
            queued = len(self._queue)
        return {
            "jobs": jobs,
            "queued": queued,
            "devices": self.devices.snapshot(),
            "workers": self.devices.workers(),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Peek-style telemetry embedding: compact per-job rows."""
        with self._lock:
            if not self._jobs:
                return {}
            rows = [{k: s[k] for k in
                     ("job_id", "kind", "tenant", "state", "chips",
                      "attempts", "migrations", "throughput")}
                    for s in (j.status() for j in self._jobs.values())]
        return {"jobs": rows, "devices": self.devices.snapshot()}

    # ------------------------------------------------- supervision loop
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                self._wake.clear()
                self._maybe_unpark()
                self._schedule_pending()
                self._poll_jobs()
                self._publish_gauges()
                self._reconcile_slo()
                self._maybe_scale_down()
                self._wake.wait(self.poll_s)
        except Exception:
            log.exception("control: scheduler loop died")
            _flight.incident("job_scheduler_died",
                             directory=self.flight_dir)

    # .......................................................... pending
    def _ready(self, job: Job) -> bool:
        return time.monotonic() >= job._not_before

    def _grant_size(self, job: Job) -> int:
        """Chips to request for this launch. Shrink-tolerant train jobs
        take the largest power of two <= min(requested, free) when the
        full gang is not available (zero shards stay balanced); at
        least ``min_chips``."""
        free = self.devices.free
        if free >= job.chips:
            return job.chips
        if isinstance(job, TrainJob) and job.shrink \
                and free >= job.min_chips:
            g = 1
            while g * 2 <= min(job.chips, free):
                g *= 2
            return max(g, job.min_chips)
        if isinstance(job, ServeJob) and free >= job.min_chips:
            return free                      # every chip = one replica
        return job.chips                     # full gang or nothing

    def _schedule_pending(self) -> None:
        while True:
            with self._lock:
                job_id = None
                # priority-then-FIFO: the stable sort keeps submission
                # order within a priority class, so an all-default
                # (priority 0) queue is exactly the PR 13 FIFO
                for jid in sorted(
                        self._queue,
                        key=lambda j: -self._jobs[j].priority):
                    j = self._jobs[jid]
                    if self._ready(j):
                        job_id = jid
                        break
                if job_id is None:
                    return
                job = self._jobs[job_id]
                want = self._grant_size(job)
                devs = self.devices.acquire(want, job.job_id)
                if devs is None:
                    self._maybe_rebalance(job)
                    self._maybe_preempt_for(job, want)
                    return                   # head keeps waiting
                self._queue.remove(job_id)
            if want != job.chips:
                _flight.record("job_migrated", job=job.job_id,
                               from_chips=job.chips, to_chips=want,
                               reason="fleet_shrunk")
                if not job._migration_counted:
                    # a preempt-requeue already counted this logical
                    # migration; only an organic shrink counts here
                    job.migrations += 1
                    if _telemetry.enabled():
                        _telemetry.MetricsRegistry.get_default() \
                            .counter(
                                _telemetry.JOBS_MIGRATIONS,
                                "job launches on a different chip "
                                "count / device set than the "
                                "previous attempt").inc(
                                job=job.job_id, reason="fleet_shrunk")
                job.chips = want
            self._launch(job, devs)

    def _maybe_preempt_for(self, job: Job, want: int) -> None:
        """Priority preemption: a gang that cannot fit may checkpoint-
        PREEMPT (never kill) running train jobs of STRICTLY lower
        priority — lowest priority first, smallest gang first — until
        the released chips would close the deficit. Victims park in a
        ``preempted`` state and resume from their own bundles when
        capacity frees (``_maybe_unpark``). Serving jobs are never
        priority-preempted: their capacity moves through the drain/
        rebalance path, which respects in-flight traffic."""
        deficit = want - self.devices.free
        if deficit <= 0:
            return
        jobs = list(self._jobs.values())
        if not any(j.priority < job.priority for j in jobs):
            return               # nobody to evict (all-default fleet)
        # chips already on their way back from in-flight preemptions
        deficit -= sum(len(j.devices) for j in jobs
                       if j._park_on_exit and j.state == "running")
        if deficit <= 0:
            return
        victims = sorted(
            (j for j in jobs
             if isinstance(j, TrainJob) and j.state == "running"
             and j.priority < job.priority
             and not (j._park_on_exit or j._cancel_on_exit
                      or j._drain_on_exit or j._migrate_on_exit)),
            key=lambda j: (j.priority, len(j.devices)))
        if sum(len(v.devices) for v in victims) < deficit:
            # evicting EVERY candidate still wouldn't seat the gang
            # (lost workers shrank the fleet below its size): parking
            # jobs buys nothing and idles the whole fleet — let the
            # gang wait while lower-priority work keeps training
            return
        for victim in victims:
            if deficit <= 0:
                return
            victim._park_on_exit = True
            victim._exit_reason = "priority_preempt"
            _count_preemption("priority", victim.job_id)
            _flight.record("job_preempt", victim=victim.job_id,
                           victim_priority=victim.priority,
                           for_job=job.job_id, priority=job.priority,
                           chips=len(victim.devices))
            log.warning(
                "control: checkpoint-preempting job %s (priority %d, "
                "%d chips) for higher-priority job %s (priority %d)",
                victim.job_id, victim.priority, len(victim.devices),
                job.job_id, job.priority)
            victim.fault_tolerance.request_preemption(kind="priority")
            deficit -= len(victim.devices)

    def _maybe_unpark(self) -> None:
        """Resume priority-preempted jobs when capacity frees: highest
        priority first, and never ahead of queued work of the same or
        higher priority (the queue got there first)."""
        with self._lock:
            parked = [j for j in self._jobs.values()
                      if j.state == "preempted"]
            if not parked:
                return
            queued_pri = [self._jobs[jid].priority
                          for jid in self._queue]
        for job in sorted(parked,
                          key=lambda j: (-j.priority, j._parked_since)):
            if any(p >= job.priority for p in queued_pri):
                continue
            if self.devices.free < max(job.min_chips, 1):
                continue
            with self._lock:
                # re-check under the lock: a concurrent cancel()/
                # drain() may have finished the parked job — a
                # terminal job must never be resurrected
                if job.state != "preempted":
                    continue
                job.transition("restarting",
                               "capacity freed — resuming")
                job._pending_since = time.monotonic()
                job._not_before = 0.0
                self._queue.append(job.job_id)
            _flight.record("job_resumed", job=job.job_id,
                           priority=job.priority)
            queued_pri.append(job.priority)

    def _maybe_rebalance(self, starved: Job) -> None:
        """Train-vs-serve rebalancing: a train job starving past
        ``rebalance_after_s`` may claim a replica from a serving job
        whose queue pressure says it won't miss it."""
        if not self.rebalance or not isinstance(starved, TrainJob):
            return
        if time.monotonic() - starved._pending_since \
                < self.rebalance_after_s:
            return
        for job in self._jobs.values():
            if not isinstance(job, ServeJob) or job.fleet is None \
                    or job.state != "running":
                continue
            fl = job.fleet
            alive = [r for r in fl._replicas
                     if r.alive and not r.draining]
            if len(alive) <= job.min_chips:
                continue
            if self._slo is not None:
                # hysteresis via the SLO engine on TOP of the one-shot
                # pressure poll: a fleet whose sustained-queue-pressure
                # alert is pending or firing (or recently flapping into
                # pending) keeps its replicas — a single idle poll
                # between two bursts no longer gives a replica away.
                # The direct poll below still applies: an engine with
                # no queue-pressure data (telemetry off, rule absent)
                # must not silently drop the pre-SLO protection.
                if self._slo.alert_state(
                        "serving_queue_pressure",
                        fleet=fl.fleet_id) in ("pending", "firing"):
                    continue
            if fl.queue_pressure() > self.rebalance_pressure:
                continue
            victim = alive[-1]
            # flag synchronously: the next scheduling pass (one poll_s
            # away) must not pick the same victim again while the
            # drain thread is still spawning
            victim.draining = True
            _flight.record("job_rebalance", frm=job.job_id,
                           to=starved.job_id,
                           replica=victim.index)
            log.warning("control: draining replica %d of %s to feed "
                        "starved train job %s", victim.index,
                        job.job_id, starved.job_id)
            # the drain blocks until in-flight requests finish — run it
            # off-loop; the freed chip flows back through the fleet's
            # capacity listener and the next scheduling pass takes it
            threading.Thread(
                target=fl.drain_replica, args=(victim.index,),
                daemon=True,
                name=f"JobRunner-rebalance-{job.job_id}").start()
            return

    # .................................................... SLO actions
    def _reconcile_slo(self) -> None:
        """Level-triggered backstop for the edge-triggered
        _on_slo_alert: a scale_serve alert that STAYS firing after a
        failed or skipped restart (the drained replica's chip was
        temporarily held by a train job, the fleet wasn't built yet)
        gets the scale-up re-attempted about once a second until it
        resolves — a deduplicated alert never re-fires its
        transition, so the subscriber alone would try exactly once."""
        if self._slo is None:
            return
        now = time.monotonic()
        if now - self._last_slo_reconcile < 1.0:
            return
        self._last_slo_reconcile = now
        try:
            firing = self._slo.alerts(states=("firing",))
        except Exception:
            return
        for a in firing:
            if getattr(a, "action", None) == "scale_serve":
                self._on_slo_alert(a)

    def _on_slo_alert(self, alert) -> None:
        """SLO-engine subscriber (runs on the SLOEvaluator thread).
        A FIRING scale_serve alert — sustained fleet queue pressure —
        restarts a drained/dead replica for the matching ServeJob;
        the restart (an engine start, possibly a compile) runs on its
        own runner thread, never on the evaluator. Resolved alerts
        just wake the loop (rebalance may now reclaim capacity)."""
        if getattr(alert, "action", None) != "scale_serve":
            return
        if alert.state != "firing":
            self._wake.set()
            return
        fleet_id = alert.labels.get("fleet")
        with self._lock:
            job = next(
                (j for j in self._jobs.values()
                 if isinstance(j, ServeJob) and j.fleet is not None
                 and j.state == "running"
                 and (fleet_id is None
                      or j.fleet.fleet_id == fleet_id)
                 and not j._scaling), None)
            if job is None:
                return
            job._scaling = True
        # snapshot the trigger value now: the Alert object is live and
        # its value will have drained back down by the time the
        # restart thread records it
        threading.Thread(
            target=self._scale_up_serve,
            args=(job, alert.rule, alert.value),
            daemon=True,
            name=f"JobRunner-scaleup-{job.job_id}").start()

    def _scale_up_serve(self, job: ServeJob, rule: str,
                        value) -> bool:
        """Give a pressured fleet capacity: restart the first drained/
        dead replica whose chip is healthy (re-acquiring the chip from
        the pool when a rebalance handed it back) — and when every
        registered replica is already serving, GROW the fleet with a
        brand-new replica on a freshly acquired chip, checkpoint-
        preempting the lowest-priority train job if the pool is empty
        (``_grow_serve``). Runs on a dedicated runner thread;
        ``job._scaling`` keeps concurrent firing ticks from
        double-restarting."""
        try:
            fleet = job.fleet
            if fleet is None or job.state != "running":
                return False
            for r in list(fleet._replicas):
                if r.alive or r.needs_cleanup:
                    continue
                dev = r.engine._device
                acquired = False
                if dev is not None:
                    if self.devices.is_lost(dev):
                        continue
                    with self._lock:
                        if dev not in job.devices:
                            if not self.devices.acquire_device(
                                    dev, job.job_id):
                                continue   # chip busy under a train job
                            job.devices.append(dev)
                            acquired = True
                try:
                    fleet.restart_replica(r.index)
                except Exception:
                    log.exception("control: SLO scale-up restart "
                                  "failed (job %s)", job.job_id)
                    if acquired:
                        with self._lock:
                            job.devices.remove(dev)
                        self.devices.release([dev])
                    continue
                _flight.record("job_scale_up", job=job.job_id,
                               replica=r.index, rule=rule,
                               value=value)
                if _telemetry.enabled():
                    _telemetry.MetricsRegistry.get_default().counter(
                        _telemetry.JOBS_RESTARTS,
                        "job component restarts (replica or whole "
                        "job)").inc(job=job.job_id,
                                    reason="queue_pressure_alert")
                log.warning("control: restarted replica %d of %s on "
                            "sustained queue-pressure alert "
                            "(value=%s)", r.index, job.job_id, value)
                return True
            return self._grow_serve(job, rule, value)
        finally:
            job._scaling = False
            self._wake.set()

    def _grow_serve(self, job: ServeJob, rule: str, value) -> bool:
        """Elastic scale-up: acquire a chip (checkpoint-preempting the
        lowest-priority strictly-lower train job when the pool is
        empty) and ``fleet.add_replica`` onto it. Every failure mode —
        no victim, the chip not freeing in time, the engine build or
        start crashing — rolls back cleanly: the chip returns to the
        pool and the parked victim is refunded automatically by
        ``_maybe_unpark`` on the next pass."""
        fleet = job.fleet
        parked = None
        devs = self.devices.acquire(1, job.job_id)
        if devs is None:
            parked = self._preempt_for_scale(job)
            if parked is None:
                _flight.record("job_scale_up_failed", job=job.job_id,
                               why="no_chip_no_victim", rule=rule)
                return False
            # the victim checkpoints and exits on its own runner
            # thread; its chips land back in the pool when the park
            # completes — bounded wait, then give up (the reconcile
            # pass retries while the alert stays firing)
            deadline = time.monotonic() + 30.0
            while devs is None and time.monotonic() < deadline \
                    and not self._stop.is_set():
                devs = self.devices.acquire(1, job.job_id)
                if devs is None:
                    time.sleep(0.05)
            if devs is None:
                _flight.record("job_scale_up_failed", job=job.job_id,
                               why="chip_not_freed", rule=rule,
                               victim=parked.job_id)
                return False
        dev = devs[0]
        with self._lock:
            if job.state != "running" or job.fleet is not fleet:
                self.devices.release([dev])
                return False
            job.devices.append(dev)
        try:
            rid = fleet.add_replica(device=dev)
        except Exception:
            log.exception("control: elastic scale-up of %s failed — "
                          "rolling back chip %s", job.job_id, dev)
            with self._lock:
                if dev in job.devices:
                    job.devices.remove(dev)
            self.devices.release([dev])
            _flight.record("job_scale_up_failed", job=job.job_id,
                           why="add_replica_failed", rule=rule)
            return False
        with self._lock:
            job._elastic.append((rid, dev))
            job._elastic_since = time.monotonic()
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.FLEET_SCALE_UP,
                "elastic serve scale-ups: replicas added on a "
                "sustained queue-pressure alert").inc(
                fleet=fleet.fleet_id)
        _flight.record("job_scale_up", job=job.job_id, replica=rid,
                       rule=rule, value=value, elastic=True,
                       victim=parked.job_id if parked is not None
                       else None)
        log.warning("control: grew fleet %s of %s to %d replicas on "
                    "sustained queue-pressure alert (value=%s%s)",
                    fleet.fleet_id, job.job_id,
                    len(fleet._replicas), value,
                    f", preempted {parked.job_id}"
                    if parked is not None else "")
        return True

    def _preempt_for_scale(self, job: ServeJob) -> Optional[Job]:
        """Checkpoint-preempt the lowest-priority running train job of
        STRICTLY lower priority than the pressured serve job (smallest
        gang breaks ties) so its chips can host a new replica. Same
        park contract as ``_maybe_preempt_for``: the victim bundles
        its state and resumes bit-identically from ``_maybe_unpark``
        when the elastic replica is later removed (or the scale-up
        rolls back)."""
        with self._lock:
            victims = sorted(
                (j for j in self._jobs.values()
                 if isinstance(j, TrainJob) and j.state == "running"
                 and j.priority < job.priority
                 and not (j._park_on_exit or j._cancel_on_exit
                          or j._drain_on_exit or j._migrate_on_exit)),
                key=lambda j: (j.priority, len(j.devices)))
            if not victims:
                return None
            victim = victims[0]
            victim._park_on_exit = True
            victim._exit_reason = "priority_preempt"
        _count_preemption("scale_serve", victim.job_id)
        _flight.record("job_preempt", victim=victim.job_id,
                       victim_priority=victim.priority,
                       for_job=job.job_id, priority=job.priority,
                       chips=len(victim.devices),
                       reason="scale_serve")
        log.warning(
            "control: checkpoint-preempting job %s (priority %d, %d "
            "chips) to grow pressured serve job %s (priority %d)",
            victim.job_id, victim.priority, len(victim.devices),
            job.job_id, job.priority)
        victim.fault_tolerance.request_preemption(kind="scale_serve")
        return victim

    def _maybe_scale_down(self) -> None:
        """Shrink-side hysteresis: an elastic replica leaves only once
        its fleet's queue-pressure alert has been continuously quiet
        (``SLOEngine.resolved_for``) for ``scale_down_hold_s`` — AND
        at least that long has passed since the last elastic
        transition, so the fresh replica gets a chance to drain the
        very pressure that summoned it. Without an SLO engine the
        direct pressure poll (same threshold the rebalancer uses)
        gates the shrink. The drain runs on its own runner thread;
        the freed chip flows back through the capacity listener and
        ``_maybe_unpark`` resumes the parked train job."""
        with self._lock:
            serving = [j for j in self._jobs.values()
                       if isinstance(j, ServeJob) and j._elastic
                       and j.fleet is not None
                       and j.state == "running" and not j._scaling]
        for job in serving:
            fl = job.fleet
            if time.monotonic() - job._elastic_since \
                    < self.scale_down_hold_s:
                continue
            if self._slo is not None:
                quiet = self._slo.resolved_for(
                    "serving_queue_pressure", fleet=fl.fleet_id)
                if quiet is None or quiet < self.scale_down_hold_s:
                    continue
            elif fl.queue_pressure() > self.rebalance_pressure:
                continue
            with self._lock:
                if not job._elastic or job._scaling \
                        or job.state != "running":
                    continue
                rid, dev = job._elastic[-1]
                job._scaling = True
            threading.Thread(
                target=self._scale_down_serve, args=(job, rid, dev),
                daemon=True,
                name=f"JobRunner-scaledown-{job.job_id}").start()

    def _remove_serve_replica(self, job: ServeJob, rid: int, dev,
                              why: str) -> bool:
        """Remove one replica from ``job``'s fleet and settle the
        chip: drain (sessions hand off to survivors), retire the id
        and its engine-labelled gauges, release the device back to
        the pool, bump the scale-down counter. Raises ValueError when
        the replica is the last one live (never shrink to zero).
        Returns True when the drain was clean."""
        fleet = job.fleet
        clean = True
        try:
            clean = fleet.remove_replica(rid)
        except IndexError:
            pass          # replica already died and left the fleet
        # the drain path released the chip through the capacity
        # listener already; the dead-replica path did not — either
        # way release() is idempotent, so settle it here
        release = False
        with self._lock:
            for lst in (job._elastic, job._manual):
                try:
                    lst.remove((rid, dev))
                except ValueError:
                    pass
            job._elastic_since = time.monotonic()
            if dev is not None and dev in job.devices:
                job.devices.remove(dev)
                release = True
        if release:
            self.devices.release([dev])
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.FLEET_SCALE_DOWN,
                "elastic serve scale-downs: replicas removed after "
                "the pressure alert stayed resolved (or on an "
                "operator's scale request)").inc(fleet=fleet.fleet_id)
        _flight.record("job_scale_down", job=job.job_id, replica=rid,
                       clean=clean, why=why)
        log.warning("control: shrank fleet %s of %s to %d replicas "
                    "(%s)", fleet.fleet_id, job.job_id,
                    len(fleet._replicas), why)
        return clean

    def _scale_down_serve(self, job: ServeJob, rid: int, dev) -> None:
        """Hysteresis-gated elastic shrink, on its own runner thread
        (the drain blocks on in-flight requests)."""
        try:
            try:
                self._remove_serve_replica(job, rid, dev,
                                           why="pressure alert quiet")
            except ValueError:
                # last live replica — never shrink to zero; drop the
                # elastic record so we stop retrying, keep the chip
                log.warning("control: skipping scale-down of %s — "
                            "replica %d is the last one live",
                            job.job_id, rid)
                with self._lock:
                    try:
                        job._elastic.remove((rid, dev))
                    except ValueError:
                        pass
        except Exception:
            log.exception("control: scale-down of %s failed",
                          job.job_id)
        finally:
            job._scaling = False
            self._wake.set()

    # ........................................................... launch
    def _launch(self, job: Job, devs: List[Any]) -> None:
        job.devices = devs
        job.attempts += 1
        job._exc = None
        job._exit_reason = None
        job._migrate_on_exit = False
        job._park_on_exit = False
        job._migration_counted = False
        job._stalled_at = None
        job._stall_deadline = None
        job.transition("running",
                       f"attempt {job.attempts} on {len(devs)} chip(s)")
        _flight.record("job_launch", job=job.job_id,
                       attempt=job.attempts, chips=len(devs),
                       devices=[str(d) for d in devs])
        if isinstance(job, TrainJob):
            ft = job.fault_tolerance
            ft.context = f"job:{job.job_id}"
            ft.on_stall = (lambda wd, j=job: self._on_stall(j, wd))
            if ft.flight_dir is None and self.flight_dir:
                ft.flight_dir = self.flight_dir
            ctx = JobContext(job, self, devs, job.attempts,
                             fault_tolerance=ft)
            t = threading.Thread(
                target=self._run_train, args=(job, ctx),
                daemon=True, name=f"JobRunner-{job.job_id}")
        else:
            ctx = JobContext(job, self, devs, job.attempts)
            t = threading.Thread(
                target=self._run_serve, args=(job, ctx),
                daemon=True, name=f"JobRunner-{job.job_id}")
        job._thread = t
        t.start()

    def _run_train(self, job: TrainJob, ctx: JobContext) -> None:
        try:
            job.result = job.run_fn(ctx)
        except BaseException as e:
            job._exc = e
        finally:
            self._wake.set()

    def _run_serve(self, job: ServeJob, ctx: JobContext) -> None:
        try:
            fleet = job.build_fn(ctx)
            fleet.start()
            if job._cancel_on_exit or job.state in TERMINAL:
                # cancelled while still building: never hand out a
                # fleet whose shutdown nobody owns
                fleet.shutdown()
                return
            fleet.capacity_listener = (
                lambda idx, dev, why, j=job: self._on_capacity(
                    j, dev, why))
            job.fleet = fleet
        except BaseException as e:
            job._exc = e
        finally:
            self._wake.set()

    def _on_capacity(self, job: ServeJob, device, why: str) -> None:
        """Fleet capacity listener. A DRAINED replica's chip goes back
        to the pool (that was the point of draining); so does a dead
        replica's chip when the chip itself is what died (it leaves
        ``job.devices`` but the pool already counts it lost). A replica
        that died on a HEALTHY chip keeps its chip assigned — the
        supervision loop restarts it there."""
        with self._lock:
            if device not in job.devices:
                return
            lost = self.devices.is_lost(device)
            if why == "drained" or lost:
                job.devices = [d for d in job.devices if d != device]
                self.devices.release([device])
        self._wake.set()

    def _on_stall(self, job: TrainJob, watchdog) -> None:
        """Watchdog expiry (timer thread): record the verdict; the
        supervision loop acts on it."""
        job._stalled_at = time.monotonic()
        _flight.record("job_stalled", job=job.job_id,
                       step=watchdog.step,
                       deadline_s=watchdog.deadline)
        self._wake.set()

    # ............................................................ polls
    def _poll_jobs(self) -> None:
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.state in TERMINAL:
                continue
            if isinstance(job, TrainJob):
                self._poll_train(job)
            elif isinstance(job, ServeJob):
                self._poll_serve(job)
            self._sample_throughput(job)

    def _poll_train(self, job: TrainJob) -> None:
        t = job._thread
        if t is not None and t.is_alive():
            # stall verdict: preempt (checkpoint at the next boundary);
            # a job that doesn't come back inside the grace window is
            # hung-dead and can only be declared, not killed (threads)
            if job._stalled_at is not None and job.state == "running":
                if not job._migrate_on_exit:
                    job._migrate_on_exit = True
                    job._exit_reason = "stalled"
                    job._stall_deadline = (time.monotonic()
                                           + job.stall_grace_s)
                    log.warning(
                        "control: job %s stalled past its watchdog "
                        "deadline — preempting for migration",
                        job.job_id)
                    job.fault_tolerance.request_preemption()
                elif job._stall_deadline is not None \
                        and time.monotonic() > job._stall_deadline:
                    job._stall_deadline = None
                    job.transition("hung",
                                   "no step boundary within grace")
                    _flight.incident(
                        "job_hung", directory=self.flight_dir,
                        job=job.job_id,
                        grace_s=job.stall_grace_s)
            return
        if t is None:
            return
        job._thread = None
        self._release_job_devices(job)
        exc = job._exc
        if exc is None:
            if job._cancel_on_exit:
                self._finish(job, "cancelled", "preempted by cancel")
            elif job._drain_on_exit:
                self._finish(job, "drained", "preempted by drain")
            elif job._park_on_exit:
                job._park_on_exit = False
                ft = job.fault_tolerance
                if ft.preemption_requested:
                    # the fit returned WITHOUT ever consuming the
                    # preemption flag: it finished its work before
                    # reaching another boundary — that is a
                    # completion, not a drain. Clear the stale flag
                    # (it would false-drain any later relaunch) and
                    # finish normally.
                    ft._preempt.clear()
                    ft._notice_box[0] = None
                    self._finish(job, "completed", "fit returned")
                else:
                    # priority preemption: checkpointed, now PARKED —
                    # no requeue; _maybe_unpark resumes it when
                    # capacity frees, bit-identically from its own
                    # bundles
                    job._parked_since = time.monotonic()
                    job.transition(
                        "preempted", "checkpoint-preempted for a "
                                     "higher-priority gang")
                    _flight.record("job_parked", job=job.job_id,
                                   priority=job.priority)
            elif job._migrate_on_exit:
                ft = job.fault_tolerance
                if ft.preemption_requested:
                    # the notice/stall preemption was never consumed:
                    # the fit completed its work first — requeueing
                    # would retrain a finished job from scratch
                    ft._preempt.clear()
                    ft._notice_box[0] = None
                    self._finish(job, "completed", "fit returned")
                else:
                    self._requeue(job,
                                  job._exit_reason or "migration",
                                  consume_retry=False)
            else:
                self._finish(job, "completed", "fit returned")
            return
        # verdict classification
        from deeplearning4j_tpu.util.resilience import DivergenceError

        if job._park_on_exit or job._migrate_on_exit:
            # a scheduler-initiated preemption (priority park or
            # maintenance notice) raced a crash: the UNCONSUMED flag
            # must not checkpoint-and-drain the relaunch at its first
            # boundary (which would read as a bogus clean completion)
            job.fault_tolerance._preempt.clear()
            job.fault_tolerance._notice_box[0] = None
        if isinstance(exc, DivergenceError):
            # the divergence guard already spent ITS budget and dumped
            # the incident (NaN-layer provenance included): restarts
            # would re-diverge — a human decision, not a retry
            self._finish(job, "failed",
                         f"divergence: {exc}", error=exc)
        elif isinstance(exc, (DeviceLostError,
                              _chaos.WorkerKilledError)):
            # a death during an announced maintenance window (the
            # notice deadline beat the step boundary) is the
            # platform's fault: one logical migration, not a retry —
            # the periodic-bundle recovery story takes over
            noticed = (job._migrate_on_exit
                       and job._exit_reason == "preempt_notice")
            self._requeue(job, f"worker_lost: {exc}",
                          consume_retry=not noticed)
        else:
            self._requeue(job, f"error: {exc}", consume_retry=True)

    def _poll_serve(self, job: ServeJob) -> None:
        t = job._thread
        if t is not None and t.is_alive():
            return
        if t is not None:
            job._thread = None
            exc = job._exc
            if exc is not None:
                self._release_job_devices(job)
                self._requeue(job, f"error: {exc}", consume_retry=True)
                return
            if job._drain_on_exit and job.state == "draining":
                self._release_job_devices(job)
                self._finish(job, "drained", "fleet drained")
                return
        fleet = job.fleet
        if fleet is None or job.state != "running":
            return
        # replica health: restart on a healthy chip, shrink off a lost
        # one (the fleet already re-routed + replayed the traffic)
        for r in fleet._replicas:
            if r.alive or r.needs_cleanup:
                continue                 # alive, or cleanup pending
            dev = r.engine._device
            if dev is not None and self.devices.is_lost(dev):
                continue                 # chip gone: stays down
            if dev is not None and dev not in job.devices:
                continue                 # chip handed back (rebalance)
            if r.draining:
                continue
            try:
                fleet.restart_replica(r.index)
                job.migrations += 1
                _flight.record("job_replica_restarted",
                               job=job.job_id, replica=r.index)
                if _telemetry.enabled():
                    _telemetry.MetricsRegistry.get_default().counter(
                        _telemetry.JOBS_RESTARTS,
                        "job component restarts (replica or whole "
                        "job)").inc(job=job.job_id,
                                    reason="replica_restart")
            except Exception:
                log.exception("control: replica restart failed "
                              "(job %s)", job.job_id)
        if fleet.alive_replicas() == 0:
            self._teardown_fleet(job, cancel_requests=False)
            self._release_job_devices(job)
            self._requeue(job, "all replicas dead",
                          consume_retry=True)

    # ..................................................... transitions
    def _requeue(self, job: Job, reason: str,
                 consume_retry: bool) -> None:
        if consume_retry:
            if job.retries_used >= job.max_retries:
                self._finish(
                    job, "failed",
                    f"retry budget exhausted ({job.max_retries}): "
                    f"{reason}",
                    error=job._exc)
                return
            job.retries_used += 1
            delay = job.backoff_s * (2 ** (job.retries_used - 1))
            job._not_before = time.monotonic() + delay
            job.transition("restarting",
                           f"{reason} (retry {job.retries_used}/"
                           f"{job.max_retries}, backoff {delay:.2f}s)")
        else:
            job.migrations += 1
            job._migration_counted = True
            job._not_before = 0.0
            job.transition("restarting", reason)
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.counter(_telemetry.JOBS_RESTARTS,
                        "job component restarts (replica or whole "
                        "job)").inc(job=job.job_id,
                                    reason="retry" if consume_retry
                                    else "migration")
            if not consume_retry:
                reg.counter(
                    _telemetry.JOBS_MIGRATIONS,
                    "job launches on a different chip count / device "
                    "set than the previous attempt").inc(
                    job=job.job_id, reason="preempt")
        job.error = reason
        job._exc = None
        with self._lock:
            job._pending_since = time.monotonic()
            self._queue.append(job.job_id)
        self._wake.set()

    def _finish(self, job: Job, state: str, reason: str,
                error: Optional[BaseException] = None) -> None:
        if state == "failed":
            # the reason carries the verdict (and embeds the exception
            # text for the error verdicts) — keep it as the headline
            job.error = reason
        elif error is not None:
            job.error = f"{type(error).__name__}: {error}"
        job.transition(state, reason)
        _flight.record("job_finished", job=job.job_id, state=state,
                       reason=reason)
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.JOBS_FINISHED,
                "jobs that reached a terminal state").inc(
                kind=job.kind, tenant=job.tenant, outcome=state)
        if state == "completed" and isinstance(job, TrainJob):
            pass   # run_fit retired the bundles itself
        if _telemetry.enabled():
            self._publish_gauges(force=True)

    def _release_job_devices(self, job: Job) -> None:
        with self._lock:
            if job.devices:
                self.devices.release(job.devices)
                job.devices = []

    def _teardown_fleet(self, job: ServeJob,
                        cancel_requests: bool) -> None:
        fleet = job.fleet
        if fleet is None:
            if job.state not in TERMINAL and job._cancel_on_exit:
                self._release_job_devices(job)
                self._finish(job, "cancelled", "cancel")
            return
        if cancel_requests:
            try:
                fleet.cancel_pending()
            except Exception:
                pass
        try:
            fleet.shutdown()
        except Exception:
            log.exception("control: fleet shutdown failed (job %s)",
                          job.job_id)
        job.fleet = None
        self._release_job_devices(job)
        if job._cancel_on_exit and job.state not in TERMINAL:
            self._finish(job, "cancelled", "cancel")

    def _drain_serve(self, job: ServeJob, timeout) -> None:
        fleet = job.fleet
        try:
            if fleet is not None:
                for r in list(fleet._replicas):
                    if r.alive:
                        fleet.drain_replica(r.index, timeout)
                fleet.shutdown()
                job.fleet = None
        except Exception as e:
            job._exc = e
        finally:
            self._wake.set()

    # ......................................................... metrics
    def _sample_throughput(self, job: Job) -> None:
        now = time.monotonic()
        # gauge cadence, not loop cadence: copying + sorting every
        # replica's recent-latency history 20x/s buys nothing
        if job._last_progress_t is not None \
                and now - job._last_progress_t < 0.5:
            return
        value = mfu = None
        unit = "steps_per_s"
        if isinstance(job, TrainJob) and job.progress is not None:
            try:
                p = job.progress()
            except Exception:
                return
            if isinstance(p, dict):
                mfu = p.get("mfu")
                p = p.get("iteration")
            if p is not None:
                value = float(p)
        elif isinstance(job, ServeJob) and job.fleet is not None:
            unit = "tokens_per_s"
            try:
                value = float(sum(r.engine.n_tokens
                                  for r in job.fleet._replicas))
            except Exception:
                return
        if value is None:
            return
        if job._last_progress_t is not None \
                and now > job._last_progress_t:
            rate = (value - job._last_progress_v) \
                / (now - job._last_progress_t)
            job.throughput = round(max(rate, 0.0), 3)
            if _telemetry.enabled():
                reg = _telemetry.MetricsRegistry.get_default()
                reg.gauge(
                    _telemetry.JOBS_THROUGHPUT,
                    "per-job progress rate (train: steps/s, serve: "
                    "tokens/s)").set(job.throughput, job=job.job_id,
                                     tenant=job.tenant, kind=job.kind,
                                     unit=unit)
                if mfu is not None:
                    reg.gauge(
                        _telemetry.JOBS_MFU,
                        "per-job model FLOPs utilization").set(
                        float(mfu), job=job.job_id, tenant=job.tenant)
        job._last_progress_v = value
        job._last_progress_t = now
        if isinstance(job, ServeJob) and job.fleet is not None \
                and _telemetry.enabled():
            lats = []
            for r in job.fleet._replicas:
                for rec in r.engine._recent.copy():
                    if rec.get("latency_ms") is not None:
                        lats.append(rec["latency_ms"])
            if lats:
                lats.sort()
                _telemetry.MetricsRegistry.get_default().gauge(
                    _telemetry.JOBS_LATENCY_P50,
                    "per-job recent request latency p50 (ms)").set(
                    lats[len(lats) // 2], job=job.job_id,
                    tenant=job.tenant)

    def _publish_gauges(self, force: bool = False) -> None:
        if not _telemetry.enabled():
            return
        now = time.monotonic()
        if not force and now - self._last_gauges < 0.5:
            return
        self._last_gauges = now
        reg = _telemetry.MetricsRegistry.get_default()
        counts: Dict[str, int] = {}
        with self._lock:
            for j in self._jobs.values():
                counts[j.state] = counts.get(j.state, 0) + 1
        g = reg.gauge(_telemetry.JOBS_RUNNING,
                      "jobs per state (pending/running/restarting/"
                      "terminal)")
        for state in ("pending", "running", "restarting", "migrating",
                      "draining", "preempted", "hung", "completed",
                      "failed", "cancelled", "drained"):
            g.set(counts.get(state, 0), state=state)
        snap = self.devices.snapshot()
        gd = reg.gauge(_telemetry.JOBS_DEVICES,
                       "scheduler device pool by status")
        for pool in ("free", "used", "lost"):
            gd.set(snap[pool], pool=pool)


# ======================================================================
# default-scheduler registry + HTTP surface
# ======================================================================
_default: Optional[JobScheduler] = None
_dlock = threading.Lock()


def set_default(scheduler: Optional[JobScheduler]) -> None:
    global _default
    with _dlock:
        _default = scheduler


def default_scheduler() -> Optional[JobScheduler]:
    return _default


def jobs_snapshot() -> Dict[str, Any]:
    """Peek-style snapshot for telemetry embedding ({} without a live
    scheduler — an idle process pays one attribute read)."""
    s = _default
    return s.snapshot() if s is not None else {}


def http_jobs_get(path: str):
    """Shared /v1/jobs GET handling for ui/server.py and
    remote/server.py. Returns (obj, http_code)."""
    s = default_scheduler()
    if s is None:
        return ({"error": "no JobScheduler in this process "
                          "(construct control.JobScheduler first)"},
                404)
    parts = [p for p in path.split("/") if p]   # v1 jobs [<id>]
    if len(parts) == 2:
        return (s.status(), 200)
    try:
        return (s.job(parts[2]).status(), 200)
    except KeyError:
        return ({"error": f"unknown job {parts[2]}"}, 404)


def http_jobs_post(path: str, payload: Dict[str, Any]):
    """Shared /v1/jobs POST handling: submit (via a registered
    factory), cancel, drain, kill_worker. Returns (obj, code)."""
    s = default_scheduler()
    if s is None:
        return ({"error": "no JobScheduler in this process"}, 404)
    parts = [p for p in path.split("/") if p]   # v1 jobs [<id> <verb>]
    try:
        if len(parts) == 2:                     # POST /v1/jobs: submit
            factory = payload.get("factory")
            if not factory:
                return ({"error": "submit needs {'factory': <name>} "
                                  "(register_factory on the "
                                  "scheduler; callables don't travel "
                                  "over JSON)"}, 400)
            job = s.submit_factory(factory,
                                   **payload.get("params", {}))
            return (job.status(), 200)
        if len(parts) == 4:
            job_id, verb = parts[2], parts[3]
            if verb == "cancel":
                return (s.cancel(job_id).status(), 200)
            if verb == "drain":
                return (s.drain(job_id).status(), 200)
        if len(parts) == 3 and parts[2] == "kill_worker":
            worker = payload.get("worker")
            if not worker:
                return ({"error": "kill_worker needs "
                                  "{'worker': <name>}"}, 400)
            if str(worker) not in s.devices.workers():
                return ({"error": f"unknown worker {worker!r} "
                                  f"(have: "
                                  f"{sorted(s.devices.workers())})"},
                        404)
            devs = s.kill_worker(worker)
            return ({"worker": str(worker),
                     "devices_lost": [str(d) for d in devs]}, 200)
        return ({"error": "not found"}, 404)
    except KeyError as e:
        return ({"error": f"unknown job/factory: {e}"}, 404)
    except Exception as e:
        return ({"error": str(e)}, 400)


def _serve_jobs(s: "JobScheduler") -> List[ServeJob]:
    with s._lock:
        return [j for j in s._jobs.values()
                if isinstance(j, ServeJob) and j.fleet is not None
                and j.state == "running"]


def _fleet_row(job: ServeJob) -> Dict[str, Any]:
    fl = job.fleet
    return {"job": job.job_id,
            "fleet": fl.fleet_id,
            "state": job.state,
            "replicas": fl.alive_replicas(),
            "registered": len(fl._replicas),
            "pending_scale": fl._pending_scale,
            "elastic": len(job._elastic),
            "manual": len(job._manual),
            "queue_pressure": fl.queue_pressure()}


def http_fleet_get(path: str):
    """Shared /v1/fleet GET handling for ui/server.py and
    remote/server.py: every running serve job's fleet — live/
    registered replica counts, pending scale ops, elastic bookkeeping
    and the queue-pressure signal. Returns (obj, http_code)."""
    s = default_scheduler()
    if s is None:
        return ({"error": "no JobScheduler in this process"}, 404)
    parts = [p for p in path.split("/") if p]   # v1 fleet [<id>]
    rows = [_fleet_row(j) for j in _serve_jobs(s)]
    if len(parts) == 3:
        sel = parts[2]
        for row in rows:
            if sel in (row["job"], row["fleet"]):
                return (row, 200)
        return ({"error": f"unknown fleet/job {sel!r}"}, 404)
    return ({"fleets": rows}, 200)


def http_fleet_post(path: str, payload: Dict[str, Any]):
    """Shared ``POST /v1/fleet/scale`` handling: drive a serve job's
    fleet to a target replica count.

    Payload: ``{"target": <int>, "job": <job_id> | "fleet":
    <fleet_id>}`` (the selector is optional when exactly one serve
    job is running). Growth acquires chips through the scheduler —
    checkpoint-preempting lower-priority training when the pool is
    empty — and the added replicas are pinned (an explicit target is
    not undone by the autoscaler's quiet-alert shrink). Shrink
    removes replicas newest-first: autoscaled first, then pinned,
    then original ones. Errors follow the /v1/jobs conventions:
    unknown job/fleet is 404, invalid targets and scale races are
    400. Returns (obj, code)."""
    s = default_scheduler()
    if s is None:
        return ({"error": "no JobScheduler in this process"}, 404)
    parts = [p for p in path.split("/") if p]   # v1 fleet scale
    if len(parts) != 3 or parts[2] != "scale":
        return ({"error": "not found"}, 404)
    try:
        target = payload.get("target")
        if target is None:
            return ({"error": "scale needs {'target': <replica "
                              "count>}"}, 400)
        target = int(target)
        if target < 1:
            return ({"error": f"target must be >= 1 (got {target})"},
                    400)
        sel = payload.get("job") or payload.get("fleet")
        jobs = _serve_jobs(s)
        if sel is not None:
            jobs = [j for j in jobs
                    if sel in (j.job_id, j.fleet.fleet_id)]
            if not jobs:
                return ({"error": f"unknown fleet/job {sel!r}"}, 404)
        if not jobs:
            return ({"error": "no running serve job"}, 404)
        if len(jobs) > 1:
            return ({"error": "multiple serve jobs running — pass "
                              "{'job': <id>} or {'fleet': <id>}"},
                    400)
        job = jobs[0]
        fleet = job.fleet
        with s._lock:
            if job._scaling:
                return ({"error": f"job {job.job_id} already has a "
                                  "scale operation in flight"}, 400)
            job._scaling = True
        try:
            while fleet.alive_replicas() < target:
                if not s._grow_serve(job, "manual_scale", target):
                    return ({"error": "scale-up failed: no chip "
                                      "available (and no lower-"
                                      "priority train job to "
                                      "preempt)",
                             **_fleet_row(job)}, 400)
                with s._lock:
                    # re-label the fresh replica as operator-pinned:
                    # explicit targets are not subject to the
                    # autoscaler's quiet-alert shrink
                    if job._elastic:
                        job._manual.append(job._elastic.pop())
            while fleet.alive_replicas() > target:
                with s._lock:
                    pool = job._elastic or job._manual
                    if pool:
                        rid, dev = pool[-1]
                    else:
                        live = [r for r in list(fleet._replicas)
                                if r.alive and not r.draining]
                        rid, dev = live[-1].rid, None
                s._remove_serve_replica(job, rid, dev,
                                        why="operator scale request")
            return (_fleet_row(job), 200)
        finally:
            job._scaling = False
            s._wake.set()
    except Exception as e:
        return ({"error": str(e)}, 400)


def _default_supervisor():
    from deeplearning4j_tpu.control.worker import default_supervisor

    return default_supervisor()


def http_workers_get(path: str):
    """Shared /v1/workers GET handling for ui/server.py and
    remote/server.py: the fleet's failure domains (scheduler view)
    and/or the supervised worker processes (supervisor view).
    Returns (obj, http_code)."""
    s = default_scheduler()
    sup = _default_supervisor()
    if s is None and sup is None:
        return ({"error": "no JobScheduler or WorkerSupervisor in "
                          "this process"}, 404)
    out: Dict[str, Any] = {}
    if s is not None:
        out["workers"] = s.devices.workers()
        out["devices"] = s.devices.snapshot()
    if sup is not None:
        out["processes"] = sup.workers_status()
        out["control_dir"] = sup.control_dir
    parts = [p for p in path.split("/") if p]   # v1 workers [<name>]
    if len(parts) == 3:
        name = parts[2]
        one = {"worker": name}
        found = False
        if name in out.get("workers", {}):
            one.update(out["workers"][name])
            found = True
        if name in out.get("processes", {}):
            one["process"] = out["processes"][name]
            found = True
        if not found:
            return ({"error": f"unknown worker {name!r}"}, 404)
        return (one, 200)
    return (out, 200)


def http_workers_post(path: str, payload: Dict[str, Any]):
    """Shared /v1/workers POST handling:

    - ``POST /v1/workers/<w>/preempt {"deadline_s": 30}`` — deliver a
      cluster maintenance notice: jobs on the worker checkpoint-and-
      drain before the deadline kill.
    - ``POST /v1/workers/<w>/restore`` — the worker's capacity
      rejoins the fleet.

    Returns (obj, code)."""
    parts = [p for p in path.split("/") if p]   # v1 workers <w> <verb>
    if len(parts) != 4:
        return ({"error": "not found"}, 404)
    name, verb = parts[2], parts[3]
    s = default_scheduler()
    sup = _default_supervisor()
    try:
        if verb == "preempt":
            deadline = float(payload.get("deadline_s", 30.0))
            if s is not None and name in s.devices.workers():
                jobs = s.preempt_worker(name, deadline_s=deadline)
                return ({"worker": name, "deadline_s": deadline,
                         "notice": "delivered", "jobs": jobs}, 200)
            if sup is not None and name in sup._handles:
                sup.preempt(name, deadline_s=deadline)
                return ({"worker": name, "deadline_s": deadline,
                         "notice": "delivered"}, 200)
            return ({"error": f"unknown worker {name!r}"}, 404)
        if verb == "restore":
            if s is not None and name in s.devices.workers():
                devs = s.restore_worker(name)
                return ({"worker": name,
                         "devices_restored": [str(d) for d in devs]},
                        200)
            return ({"error": f"unknown worker {name!r}"}, 404)
        return ({"error": "not found"}, 404)
    except Exception as e:
        return ({"error": str(e)}, 400)


__all__ = ["JobScheduler", "TrainJob", "ServeJob", "Job", "JobContext",
           "DeviceFleet", "DeviceLostError", "TERMINAL",
           "set_default", "default_scheduler", "jobs_snapshot",
           "http_jobs_get", "http_jobs_post",
           "http_workers_get", "http_workers_post",
           "http_fleet_get", "http_fleet_post"]
