"""Control plane phase 2: workers as supervised OS processes.

PR 13's ``JobScheduler`` made survival a first-class behavior, but a
"worker" was still an in-process failure domain: a hard host death was
only emulated (``inject_fault``), a preemption was something tests
requested, and every bundle lived on the dying process's own disk.
This module closes those three gaps:

- **Real processes.** ``WorkerSupervisor`` spawns one OS process per
  worker (``python -m deeplearning4j_tpu.control.worker``), each
  heartbeating over a file lease in a shared control directory. A
  process that exits — or whose lease goes stale — is DEAD the way a
  host is dead: nothing in it gets to clean up. The supervisor maps
  that death onto the scheduler's existing verdict path
  (``lose_worker`` + ``DeviceLostError`` → recover-newest-bundle-and-
  migrate) and, when the restart budget allows, respawns the worker —
  whose first heartbeat restores its capacity to the fleet
  (``restore_worker``).
- **Notices that arrive.** ``supervisor.preempt(worker, deadline_s)``
  delivers a GCE/Borg-style maintenance event: a ``notice.json`` the
  worker's ``NoticePoller`` converts into
  ``FaultTolerance.request_preemption(deadline_s, kind="metadata")``,
  so the task checkpoints and drains BEFORE the kill. At the deadline
  the supervisor enforces the platform contract — a worker still
  running its task is SIGKILLed, and recovery degrades to the newest
  periodic bundle.
- **Tasks that migrate.** ``submit_task`` queues work (an ``entry``
  of the form ``"module:function"`` called with a ``WorkerTaskContext``)
  onto any alive worker. A task whose worker died is re-assigned to a
  survivor; with its bundles in a ``SharedFSBundleStore`` the
  survivor's ``auto_resume`` finds the dead host's checkpoint and the
  run continues bit-identically.

The control directory is the entire protocol (no sockets, no pickles —
any host that mounts it can participate)::

    <control_dir>/<worker>/
        heartbeat.json        worker -> supervisor, every heartbeat_s
        task.json             supervisor -> worker (the assignment)
        notice.json           supervisor -> worker (maintenance event)
        result-<task>.json    worker -> supervisor (outcome)
        metrics.json          worker -> supervisor (federated registry
                              capture, when DL4J_TPU_TSDB=1 — ingested
                              into the coordinator's time-series store
                              under worker=/host= labels; see
                              profiler/timeseries.py)
        worker.log            the process's stdout+stderr

Multi-host meshes ride the existing ``jax.distributed`` seam: a
supervisor constructed with ``coordinator=`` injects the
``DL4J_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` env vars
(``parallel.mesh.worker_env``) so spawned workers join one mesh via
``maybe_init_distributed()``.

Supervisor-off identity: nothing here is imported by the scheduler,
the fit loops, or the serving engine unless a supervisor is
constructed — the in-process control plane is byte-for-byte the PR 13
code path.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")

HEARTBEAT = "heartbeat.json"
TASK = "task.json"
NOTICE = "notice.json"
METRICS = "metrics.json"

#: task outcomes a worker reports
OUTCOMES = ("completed", "preempted", "failed")


def _write_json_atomic(path: str, obj: Dict[str, Any]) -> None:
    """tmp + fsync + rename via the resume-bundle helpers
    (util/model_serializer): a reader never sees a torn JSON file,
    and the rename is made durable (atomic_replace fsyncs the parent
    directory — a power cut can't un-publish a result/notice)."""
    from deeplearning4j_tpu.util.model_serializer import (
        atomic_replace, unique_tmp_path,
    )

    tmp = unique_tmp_path(path)
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    atomic_replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ======================================================================
# the worker process (runs via ``python -m ...control.worker``)
# ======================================================================
class WorkerTaskContext:
    """What a task entry function receives: its parameters, the
    worker-configured FaultTolerance policy (preemption notices land
    on it — a fit MUST pass it to ``fit(..., fault_tolerance=...)``),
    and a ``progress(step)`` hook that feeds the heartbeat so the
    supervisor (and its liveness gauges) see live step counts."""

    def __init__(self, worker: str, task_id: str,
                 params: Dict[str, Any], attempt: int,
                 fault_tolerance, report: Callable[[int], None]):
        self.worker = worker
        self.task_id = task_id
        self.params = dict(params or {})
        self.attempt = int(attempt)
        self.fault_tolerance = fault_tolerance
        self._report = report
        #: a task that exits EARLY because of a preemption notice
        #: (without writing a checkpoint — e.g. a cooperative loop)
        #: sets this so the supervisor re-queues it; fits don't need
        #: it (their preemption checkpoint is the drain signal), and
        #: a task that ran to completion leaves it False even if a
        #: notice landed after its last boundary
        self.drained = False

    def progress(self, step: int) -> None:
        self._report(int(step))

    @property
    def preemption_requested(self) -> bool:
        ft = self.fault_tolerance
        return bool(ft is not None and ft.preemption_requested)


def _resolve_entry(entry: str) -> Callable:
    """``"module:function"`` -> the callable (module importable on the
    worker's sys.path; the supervisor puts the control dir there so
    drills can drop task modules next to the protocol files)."""
    import importlib

    mod_name, _, fn_name = entry.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"task entry {entry!r} is not 'module:function'")
    return getattr(importlib.import_module(mod_name), fn_name)


def _build_ft(spec: Optional[Dict[str, Any]]):
    """FaultTolerance from the task's JSON ``ft`` spec. A
    ``shared_root`` (+ optional ``namespace``) becomes a
    SharedFSBundleStore — the cross-host discovery that lets a
    survivor resume a dead worker's run; every other key passes
    through to the policy constructor."""
    from deeplearning4j_tpu.util.resilience import (
        FaultTolerance, SharedFSBundleStore,
    )

    spec = dict(spec or {})
    store = None
    root = spec.pop("shared_root", None)
    namespace = spec.pop("namespace", "default")
    if root:
        store = SharedFSBundleStore(root, namespace)
    return FaultTolerance(bundle_store=store, **spec)


def echo_task(ctx: WorkerTaskContext) -> Dict[str, Any]:
    """Built-in smoke task: round-trips its params (proves the spawn/
    assign/run/result protocol without touching jax)."""
    return {"echo": ctx.params, "worker": ctx.worker,
            "attempt": ctx.attempt}


def spin_task(ctx: WorkerTaskContext) -> Dict[str, Any]:
    """Built-in drill task: spins for ``seconds`` (default: forever),
    draining early on a preemption notice — the no-jax way to exercise
    notices, SIGKILL-mid-task, and migration. Each step also ticks a
    counter in THIS process's registry, so federation drills have a
    worker-side series to watch arrive coordinator-side."""
    deadline = (time.monotonic() + float(ctx.params["seconds"])
                if "seconds" in ctx.params else None)
    step = 0
    drill = _telemetry.MetricsRegistry.get_default().counter(
        "dl4j_tpu_worker_drill_steps_total",
        "spin_task steps (metric-federation drill)")
    while deadline is None or time.monotonic() < deadline:
        if ctx.preemption_requested:
            ctx.drained = True
            return {"drained_at_step": step}
        step += 1
        ctx.progress(step)
        drill.inc()
        time.sleep(0.02)
    return {"steps": step}


class _WorkerMain:
    """The worker process body: heartbeat thread + task/notice loop."""

    def __init__(self, control_dir: str, name: str,
                 heartbeat_s: float = 0.2, metrics_s: float = 0.5):
        self.dir = os.path.join(control_dir, name)
        os.makedirs(self.dir, exist_ok=True)
        self.name = name
        self.heartbeat_s = float(heartbeat_s)
        self.metrics_s = float(metrics_s)
        self._lock = threading.Lock()
        self._state = {"state": "idle", "task": None, "step": 0}
        self._seq = 0
        self._stop = threading.Event()
        self._ft = None           # the running task's policy
        self._done_tasks: set = set()
        #: metric federation rides the heartbeat loop, gated on the
        #: inherited DL4J_TPU_TSDB opt-in (checked once here so an
        #: off-mode worker never imports the timeseries module)
        self._metrics_on = os.environ.get(
            "DL4J_TPU_TSDB", "0") not in ("0", "", "false")

    # -------------------------------------------------------- heartbeat
    def _beat_once(self) -> None:
        with self._lock:
            self._seq += 1
            payload = dict(self._state, t=time.time(), pid=os.getpid(),
                           seq=self._seq, worker=self.name)
        _write_json_atomic(os.path.join(self.dir, HEARTBEAT), payload)

    def _beat_loop(self) -> None:
        next_metrics = 0.0
        while not self._stop.is_set():
            try:
                self._beat_once()
            except OSError:
                pass              # control dir hiccup: next beat retries
            if self._metrics_on \
                    and time.monotonic() >= next_metrics:
                next_metrics = time.monotonic() + self.metrics_s
                self._publish_metrics()
            self._stop.wait(self.heartbeat_s)

    def _publish_metrics(self) -> None:
        """Federate this process's registry: an encoded capture next
        to the heartbeat, atomically replaced each cadence — the
        supervisor ingests it into the coordinator's time-series
        store under ``worker=``/``host=`` labels. Never raises (a
        full control volume must not kill the heartbeat loop)."""
        try:
            import socket

            from deeplearning4j_tpu.profiler import timeseries as _ts

            if not _ts.enabled():
                return
            cap = _telemetry.MetricsRegistry.get_default().capture()
            if not cap:
                return
            _write_json_atomic(
                os.path.join(self.dir, METRICS),
                {"worker": self.name, "host": socket.gethostname(),
                 "t": time.time(),
                 "capture": _ts.encode_capture(cap)})
        except Exception:
            log.debug("worker %s: metrics publish failed", self.name,
                      exc_info=True)

    def _set(self, **kw) -> None:
        with self._lock:
            self._state.update(kw)

    # ----------------------------------------------------------- signals
    def _install_signals(self) -> None:
        def _sigterm(signum, frame):
            ft = self._ft
            if ft is not None:
                # mid-task: behave like a platform grace period — the
                # policy checkpoints at the next boundary and the task
                # returns "preempted"
                ft.request_preemption(kind="signal")
            else:
                raise SystemExit(0)

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except (ValueError, OSError):
            pass

    # -------------------------------------------------------------- task
    def _run_task(self, spec: Dict[str, Any]) -> None:
        task_id = spec["task_id"]
        self._set(state="running", task=task_id, step=0)
        from deeplearning4j_tpu.util.resilience import NoticePoller

        ft = _build_ft(spec.get("ft"))
        self._ft = ft
        poller = NoticePoller(ft, file=os.path.join(self.dir, NOTICE),
                              poll_s=min(self.heartbeat_s, 0.1))
        poller.start()
        before = ft.preemptions_checkpointed
        result: Dict[str, Any] = {"task_id": task_id,
                                  "worker": self.name,
                                  "attempt": spec.get("attempt", 1)}
        try:
            fn = _resolve_entry(spec["entry"])
            ctx = WorkerTaskContext(
                self.name, task_id, spec.get("params"),
                spec.get("attempt", 1), ft,
                report=lambda s: self._set(step=s))
            value = fn(ctx)
            # drained = a preemption CHECKPOINT was written (a fit
            # honored the notice) or the entry declared a cooperative
            # early exit (ctx.drained). A raw still-set flag is NOT
            # enough: a notice landing after the fit's last boundary
            # must not re-queue a task that actually finished.
            preempted = (ft.preemptions_checkpointed > before
                         or ctx.drained)
            result["outcome"] = "preempted" if preempted else "completed"
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            result["result"] = value
            store = ft.store()
            if preempted and store is not None:
                result["bundle"] = store.latest_valid()
        except BaseException as e:   # the result file IS the report
            result["outcome"] = "failed"
            result["error"] = f"{type(e).__name__}: {e}"
            log.exception("worker %s: task %s failed", self.name,
                          task_id)
        finally:
            poller.stop()
            self._ft = None
        _write_json_atomic(
            os.path.join(self.dir, f"result-{task_id}.json"), result)
        self._done_tasks.add(task_id)
        if result["outcome"] == "preempted":
            # the platform is about to take this host: report, then
            # leave. The supervisor respawns us when the window passes.
            self._set(state="drained", task=None)
            self._beat_once()
            raise SystemExit(0)
        self._set(state="idle", task=None, step=0)

    # -------------------------------------------------------------- loop
    def run(self) -> int:
        self._install_signals()
        beat = threading.Thread(target=self._beat_loop, daemon=True,
                                name="WorkerHeartbeat")
        beat.start()
        log.warning("worker %s up (pid %d, control dir %s)", self.name,
                    os.getpid(), self.dir)
        try:
            while True:
                notice = _read_json(os.path.join(self.dir, NOTICE))
                if notice is not None and self._ft is None:
                    # idle worker noticed: nothing to checkpoint —
                    # drain immediately
                    self._set(state="drained")
                    self._beat_once()
                    return 0
                spec = _read_json(os.path.join(self.dir, TASK))
                if spec is not None \
                        and spec.get("task_id") not in self._done_tasks:
                    self._run_task(spec)
                time.sleep(0.05)
        except SystemExit:
            return 0
        finally:
            self._stop.set()


def main(argv: Sequence[str]) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="deeplearning4j_tpu supervised worker process")
    p.add_argument("control_dir")
    p.add_argument("name")
    p.add_argument("--heartbeat-s", type=float, default=0.2)
    args = p.parse_args(list(argv))
    return _WorkerMain(args.control_dir, args.name,
                       args.heartbeat_s).run()


# ======================================================================
# the supervisor
# ======================================================================
class WorkerTask:
    """Supervisor-side task record + client handle."""

    def __init__(self, entry: str, params: Optional[Dict[str, Any]],
                 ft: Optional[Dict[str, Any]], *,
                 task_id: Optional[str] = None,
                 worker: Optional[str] = None,
                 resume: bool = True, max_migrations: int = 3):
        self.task_id = task_id or f"task-{uuid.uuid4().hex[:8]}"
        self.entry = str(entry)
        self.params = dict(params or {})
        self.ft = dict(ft or {})
        self.pinned = worker       # explicit placement, or None = any
        self.resume = bool(resume)
        self.max_migrations = int(max_migrations)
        self.state = "queued"      # queued|running|completed|preempted|
        #                            failed|cancelled
        self.worker: Optional[str] = None
        self.attempts = 0
        self.migrations = 0
        self.excluded: set = set()
        self.result: Any = None
        self.bundle: Optional[str] = None
        self.error: Optional[str] = None
        self._finished = threading.Event()

    @property
    def done(self) -> bool:
        return self._finished.is_set()

    def wait(self, timeout: Optional[float] = None) -> "WorkerTask":
        if not self._finished.wait(timeout):
            raise TimeoutError(
                f"task {self.task_id} still {self.state} after "
                f"{timeout}s")
        return self

    def status(self) -> Dict[str, Any]:
        return {"task_id": self.task_id, "entry": self.entry,
                "state": self.state, "worker": self.worker,
                "attempts": self.attempts,
                "migrations": self.migrations, "error": self.error,
                "bundle": self.bundle}


class _WorkerHandle:
    """Supervisor-side per-worker-process record."""

    def __init__(self, name: str, directory: str):
        self.name = name
        self.dir = directory
        self.proc: Optional[subprocess.Popen] = None
        self.state = "stopped"    # starting|alive|dead|drained|stopped
        self.restarts = 0
        self.task: Optional[WorkerTask] = None
        self.last_seq = -1
        self.last_seen = time.monotonic()
        self.last_beat: Dict[str, Any] = {}
        self.not_before = 0.0     # respawn backoff gate
        self.notice_deadline: Optional[float] = None
        #: next respawn is a maintenance-window return, not a crash
        #: recovery — it must not consume the restart budget
        self.respawn_free = False
        #: the worker was down (crash OR drain) since its last alive —
        #: the next first-heartbeat must restore fleet capacity
        self.was_down = False
        #: newest federated metrics.json timestamp already ingested
        self.last_metrics_t = 0.0

    def beat_age(self) -> float:
        return time.monotonic() - self.last_seen


class WorkerSupervisor:
    """Spawn, lease-monitor, preempt, and restart worker processes
    (module docstring). Construct, ``start()``, then ``submit_task``
    — or attach to a ``JobScheduler`` (``scheduler=`` here, or
    ``JobScheduler(supervisor=...)``) so process death and recovery
    drive the fleet's ``lose_worker``/``restore_worker`` capacity.

    ``lease_s`` is the liveness contract: a worker whose heartbeat
    file goes stale that long is presumed dead and hard-killed (a
    half-dead process must not keep writing to shared state after the
    fleet moved on — the same fencing reason real leases exist)."""

    def __init__(self, workers: Sequence[str] = ("w0", "w1"), *,
                 control_dir: Optional[str] = None,
                 heartbeat_s: float = 0.2, lease_s: float = 3.0,
                 poll_s: float = 0.1,
                 restart_workers: bool = True, max_restarts: int = 3,
                 restart_delay_s: float = 0.25,
                 scheduler=None, env: Optional[Dict[str, str]] = None,
                 python: Optional[str] = None,
                 coordinator: Optional[str] = None,
                 make_default: bool = True):
        self.control_dir = control_dir or tempfile.mkdtemp(
            prefix="dl4j_workers_")
        self.heartbeat_s = float(heartbeat_s)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.restart_workers = bool(restart_workers)
        self.max_restarts = int(max_restarts)
        self.restart_delay_s = float(restart_delay_s)
        self.scheduler = scheduler
        self.env = dict(env or {})
        self.python = python or sys.executable
        self.coordinator = coordinator
        self._names = [str(w) for w in workers]
        self._handles: Dict[str, _WorkerHandle] = {
            n: _WorkerHandle(
                n, os.path.join(self.control_dir, n))
            for n in self._names}
        self._tasks: Dict[str, WorkerTask] = {}
        self._queue: List[str] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_gauges = 0.0
        if scheduler is not None and hasattr(scheduler,
                                             "attach_supervisor"):
            scheduler.attach_supervisor(self)
        if make_default:
            set_default_supervisor(self)

    # -------------------------------------------------------- lifecycle
    def start(self) -> "WorkerSupervisor":
        with self._lock:
            if self._thread is not None:
                return self
            for name in self._names:
                self._spawn(name)
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="WorkerSupervisor")
            self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        deadline = time.monotonic() + timeout
        for h in self._handles.values():
            p = h.proc
            if p is None or p.poll() is not None:
                continue
            try:
                p.terminate()
            except OSError:
                pass
        for h in self._handles.values():
            p = h.proc
            if p is None:
                continue
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(5)
            h.state = "stopped"
        with self._lock:
            for task in self._tasks.values():
                if not task.done:
                    task.state = "cancelled"
                    task._finished.set()
        if default_supervisor() is self:
            set_default_supervisor(None)

    def __enter__(self) -> "WorkerSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------ spawn
    def _worker_env(self, name: str) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.env)
        # the control dir rides the worker's sys.path so drills can
        # drop task modules right next to the protocol files; the
        # package root rides along so the spawned interpreter resolves
        # deeplearning4j_tpu regardless of its cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        extra = self.control_dir + os.pathsep + pkg_root
        env["PYTHONPATH"] = (extra + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else extra)
        if self.coordinator:
            from deeplearning4j_tpu.parallel.mesh import worker_env

            env.update(worker_env(self.coordinator, len(self._names),
                                  self._names.index(name)))
        return env

    def _spawn(self, name: str) -> None:
        h = self._handles[name]
        os.makedirs(h.dir, exist_ok=True)
        # never let a new incarnation act on the previous one's inputs
        for fname in (TASK, NOTICE, HEARTBEAT, METRICS):
            try:
                os.remove(os.path.join(h.dir, fname))
            except OSError:
                pass
        logf = open(os.path.join(h.dir, "worker.log"), "ab")
        try:
            h.proc = subprocess.Popen(
                [self.python, "-m",
                 "deeplearning4j_tpu.control.worker", self.control_dir,
                 name, "--heartbeat-s", str(self.heartbeat_s)],
                stdout=logf, stderr=subprocess.STDOUT,
                env=self._worker_env(name))
        finally:
            logf.close()
        h.state = "starting"
        h.last_seq = -1
        h.last_seen = time.monotonic()
        h.last_beat = {}         # never read a dead incarnation's beat
        h.notice_deadline = None
        _flight.record("worker_process_spawn", worker=name,
                       pid=h.proc.pid, restarts=h.restarts)
        log.warning("supervisor: spawned worker %s (pid %d)", name,
                    h.proc.pid)

    # ------------------------------------------------------------ client
    def submit_task(self, entry: str,
                    params: Optional[Dict[str, Any]] = None, *,
                    ft: Optional[Dict[str, Any]] = None,
                    worker: Optional[str] = None,
                    resume: bool = True,
                    max_migrations: int = 3) -> WorkerTask:
        task = WorkerTask(entry, params, ft, worker=worker,
                          resume=resume, max_migrations=max_migrations)
        with self._lock:
            self._tasks[task.task_id] = task
            self._queue.append(task.task_id)
        _flight.record("worker_task_submit", task=task.task_id,
                       entry=entry, worker=worker)
        self.start()
        return task

    def task(self, task_id: str) -> WorkerTask:
        with self._lock:
            return self._tasks[task_id]

    def preempt(self, worker: str, deadline_s: float = 30.0,
                kind: str = "notice") -> None:
        """Deliver a maintenance notice: the worker checkpoints and
        drains within ``deadline_s``; at the deadline a worker still
        running its task is SIGKILLed (the platform doesn't wait) and
        recovery degrades to the newest periodic bundle."""
        h = self._handles[str(worker)]
        _write_json_atomic(
            os.path.join(h.dir, NOTICE),
            {"deadline_s": float(deadline_s), "t": time.time(),
             "kind": kind})
        h.notice_deadline = time.monotonic() + float(deadline_s)
        _flight.record("worker_preempt_notice", worker=str(worker),
                       deadline_s=deadline_s, notice_kind=kind)
        log.warning("supervisor: maintenance notice for worker %s "
                    "(deadline %.1fs)", worker, deadline_s)

    def kill(self, worker: str) -> None:
        """SIGKILL a worker process — the chaos drill's hard host
        death (no notice, no grace, no cleanup)."""
        h = self._handles[str(worker)]
        p = h.proc
        _flight.record("worker_process_kill", worker=str(worker))
        if p is not None and p.poll() is None:
            p.kill()

    # ------------------------------------------------------------ status
    def workers_status(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        with self._lock:
            for name, h in self._handles.items():
                out[name] = {
                    "state": h.state,
                    "pid": h.proc.pid if h.proc else None,
                    "restarts": h.restarts,
                    "heartbeat_age_s": round(h.beat_age(), 3),
                    "step": h.last_beat.get("step"),
                    "task": h.task.task_id if h.task else None,
                }
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            tasks = [t.status() for t in self._tasks.values()]
        return {"workers": self.workers_status(), "tasks": tasks,
                "control_dir": self.control_dir}

    def alive(self) -> List[str]:
        with self._lock:
            return [n for n, h in self._handles.items()
                    if h.state == "alive"]

    # ---------------------------------------------------------- monitor
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._poll_workers()
                self._assign_tasks()
                self._publish_gauges()
            except Exception:
                log.exception("supervisor: monitor pass failed")
            self._stop.wait(self.poll_s)

    def _poll_workers(self) -> None:
        now = time.monotonic()
        for name, h in self._handles.items():
            if h.proc is None:
                if h.state == "dead" and self.restart_workers \
                        and now >= h.not_before \
                        and (h.respawn_free
                             or h.restarts < self.max_restarts):
                    if h.respawn_free:
                        # maintenance-window return: planned, budget
                        # untouched — only crashes spend max_restarts
                        h.respawn_free = False
                    else:
                        h.restarts += 1
                    self._spawn(name)
                continue
            beat = _read_json(os.path.join(h.dir, HEARTBEAT))
            if beat is not None and beat.get("seq", -1) != h.last_seq:
                h.last_seq = beat.get("seq", -1)
                h.last_seen = now
                h.last_beat = beat
                if h.state == "starting":
                    self._on_worker_alive(h)
            self._ingest_worker_metrics(h)
            self._collect_result(h)
            rc = h.proc.poll()
            if rc is not None:
                drained = (h.last_beat.get("state") == "drained"
                           or (h.task is None and rc == 0))
                h.proc = None
                if drained and h.task is None:
                    h.state = "drained" if h.notice_deadline else "dead"
                    if h.state == "drained":
                        _flight.record("worker_process_drained",
                                       worker=name)
                        # respawn when the maintenance window passes
                        # — a planned return, free of restart budget
                        h.state = "dead"
                        h.was_down = True
                        h.respawn_free = True
                        h.not_before = h.notice_deadline or now
                        h.notice_deadline = None
                        continue
                self._on_worker_dead(h, f"process exited rc={rc}")
            elif h.state == "alive" and h.beat_age() > self.lease_s:
                # stale lease: fence the half-dead process, then treat
                # it exactly like a host death
                try:
                    h.proc.kill()
                    h.proc.wait(5)
                except OSError:
                    pass
                h.proc = None
                self._on_worker_dead(
                    h, f"heartbeat lease expired "
                       f"({h.beat_age():.1f}s > {self.lease_s}s)")
            elif h.notice_deadline is not None \
                    and now > h.notice_deadline:
                # the maintenance window closed and the worker is
                # still up: the platform kill lands NOW
                h.notice_deadline = None
                log.warning("supervisor: worker %s missed its notice "
                            "deadline — killing", name)
                try:
                    h.proc.kill()
                except OSError:
                    pass

    def _ingest_worker_metrics(self, h: _WorkerHandle) -> None:
        """Hand a fresh worker ``metrics.json`` to the coordinator's
        time-series sampler (``Sampler.ingest_remote``), which merges
        it into each tick under ``worker=``/``host=`` labels so range
        queries and SLO rules see the whole cluster. sys.modules-
        guarded: a supervisor in a TSDB-off process never imports
        (let alone feeds) the store."""
        _ts = sys.modules.get(
            "deeplearning4j_tpu.profiler.timeseries")
        if _ts is None:
            return
        sampler = _ts.default_sampler()
        if sampler is None:
            return
        obj = _read_json(os.path.join(h.dir, METRICS))
        if not obj:
            return
        try:
            t = float(obj.get("t", 0.0))
        except (TypeError, ValueError):
            return
        if t <= h.last_metrics_t:
            return                 # already ingested this capture
        cap = _ts.decode_capture(obj.get("capture") or {})
        if not cap:
            return
        h.last_metrics_t = t
        sampler.ingest_remote(cap, worker=h.name,
                              host=obj.get("host"), t=t)

    def _collect_result(self, h: _WorkerHandle) -> None:
        task = h.task
        if task is None:
            return
        res = _read_json(
            os.path.join(h.dir, f"result-{task.task_id}.json"))
        if res is None:
            return
        h.task = None
        outcome = res.get("outcome", "failed")
        task.worker = h.name
        task.result = res.get("result")
        task.bundle = res.get("bundle")
        task.error = res.get("error")
        if outcome == "preempted" and task.resume \
                and task.migrations < task.max_migrations:
            # checkpointed clean drain: the task itself continues on
            # another worker (the bundle store is how it finds its
            # own state)
            task.state = "preempted"
            task.migrations += 1
            task.excluded.add(h.name)
            with self._lock:
                self._queue.append(task.task_id)
            _flight.record("worker_task_migrated", task=task.task_id,
                           frm=h.name, reason="preempt_notice")
            return
        task.state = outcome
        task._finished.set()
        _flight.record("worker_task_finished", task=task.task_id,
                       worker=h.name, outcome=outcome)

    def _on_worker_alive(self, h: _WorkerHandle) -> None:
        h.state = "alive"
        _flight.record("worker_process_alive", worker=h.name,
                       pid=h.last_beat.get("pid"),
                       restarts=h.restarts)
        log.warning("supervisor: worker %s alive (pid %s)", h.name,
                    h.last_beat.get("pid"))
        sched = self.scheduler
        if sched is not None and h.was_down:
            # every return from a down period restores capacity —
            # crash respawns AND maintenance-window returns (the
            # latter never touch the restart budget)
            try:
                sched.on_worker_process_alive(h.name)
            except Exception:
                log.exception("supervisor: scheduler restore hook "
                              "failed for %s", h.name)
        h.was_down = False

    def _on_worker_dead(self, h: _WorkerHandle, why: str) -> None:
        h.state = "dead"
        h.was_down = True
        h.not_before = time.monotonic() + self.restart_delay_s
        _flight.record("worker_process_dead", worker=h.name, why=why)
        log.warning("supervisor: worker %s DEAD (%s)", h.name, why)
        task = h.task
        if task is not None:
            h.task = None
            task.excluded.add(h.name)
            if task.resume and task.migrations < task.max_migrations:
                task.state = "queued"
                task.migrations += 1
                with self._lock:
                    self._queue.append(task.task_id)
                _flight.record("worker_task_migrated",
                               task=task.task_id, frm=h.name,
                               reason="worker_dead")
                log.warning("supervisor: task %s migrates off dead "
                            "worker %s", task.task_id, h.name)
            else:
                task.state = "failed"
                task.error = f"worker {h.name} died: {why}"
                task._finished.set()
        sched = self.scheduler
        if sched is not None:
            try:
                sched.on_worker_process_dead(h.name, why)
            except Exception:
                log.exception("supervisor: scheduler verdict hook "
                              "failed for %s", h.name)

    def _assign_tasks(self) -> None:
        with self._lock:
            queue = list(self._queue)
        for task_id in queue:
            task = self._tasks.get(task_id)
            if task is None or task.done:
                with self._lock:
                    if task_id in self._queue:
                        self._queue.remove(task_id)
                continue
            target = None
            blocked_only_by_exclusion = False
            for name, h in self._handles.items():
                if h.state != "alive" or h.task is not None:
                    continue
                if task.pinned is not None and name != task.pinned:
                    continue
                if name in task.excluded:
                    blocked_only_by_exclusion = True
                    continue
                target = h
                break
            if target is None:
                if blocked_only_by_exclusion:
                    # every schedulable worker is excluded — but an
                    # exclusion only means "not the incarnation that
                    # just died/drained"; an ALIVE worker is a fresh
                    # incarnation, so stale exclusions are lifted
                    # rather than leaving the task queued forever
                    task.excluded.clear()
                continue          # no capacity yet: stays queued
            task.attempts += 1
            task.state = "running"
            task.worker = target.name
            target.task = task
            _write_json_atomic(
                os.path.join(target.dir, TASK),
                {"task_id": task.task_id, "entry": task.entry,
                 "params": task.params, "ft": task.ft,
                 "attempt": task.attempts})
            with self._lock:
                self._queue.remove(task_id)
            _flight.record("worker_task_assign", task=task.task_id,
                           worker=target.name, attempt=task.attempts)

    # ----------------------------------------------------------- gauges
    def _publish_gauges(self, force: bool = False) -> None:
        if not _telemetry.enabled():
            return
        now = time.monotonic()
        if not force and now - self._last_gauges < 0.5:
            return
        self._last_gauges = now
        reg = _telemetry.MetricsRegistry.get_default()
        counts: Dict[str, int] = {}
        with self._lock:
            for h in self._handles.values():
                counts[h.state] = counts.get(h.state, 0) + 1
            # EVERY worker publishes an age: a dead/unspawned
            # worker's age keeps CLIMBING (last_seen froze at its
            # final beat) instead of the series freezing at a small
            # healthy-looking value — the operator's "age climbing
            # toward lease_s / beyond it" read stays truthful
            ages = {n: h.beat_age() for n, h in self._handles.items()}
        g = reg.gauge(_telemetry.WORKER_PROCESSES,
                      "supervised worker processes by state")
        for state in ("starting", "alive", "dead", "drained",
                      "stopped"):
            g.set(counts.get(state, 0), state=state)
        ga = reg.gauge(_telemetry.WORKER_HEARTBEAT_AGE,
                       "seconds since each worker's last heartbeat "
                       "(climbs unbounded while a worker is down)")
        for name, age in ages.items():
            ga.set(round(age, 3), worker=name)


# ======================================================================
# default-supervisor registry (HTTP surface parity with the scheduler)
# ======================================================================
_default_sup: Optional[WorkerSupervisor] = None
_sup_lock = threading.Lock()


def set_default_supervisor(sup: Optional[WorkerSupervisor]) -> None:
    global _default_sup
    with _sup_lock:
        _default_sup = sup


def default_supervisor() -> Optional[WorkerSupervisor]:
    return _default_sup


def workers_snapshot() -> Dict[str, Any]:
    """Peek-style snapshot for telemetry embedding ({} without a live
    supervisor)."""
    s = _default_sup
    return s.snapshot() if s is not None else {}


__all__ = ["WorkerSupervisor", "WorkerTask", "WorkerTaskContext",
           "echo_task", "spin_task", "main",
           "set_default_supervisor", "default_supervisor",
           "workers_snapshot"]


if __name__ == "__main__":       # pragma: no cover - subprocess entry
    sys.exit(main(sys.argv[1:]))
