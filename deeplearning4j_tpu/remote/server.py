"""HTTP JSON inference server + client.

Reference: deeplearning4j-remote — org/deeplearning4j/remote/
JsonModelServer (serves MultiLayerNetwork / ComputationGraph / SameDiff
over HTTP JSON with pluggable serializers) and JsonRemoteInference (the
client), SURVEY.md §2.36.

Endpoints (stdlib http.server, daemon thread):
    POST /v1/serving/predict   {"features": <nested list>, ...}
                               -> {"output": <nested list>}
    GET  /v1/serving/info      -> model metadata

Batching note: requests are served one-by-one; the TPU-side win comes
from the jit-compiled forward reused across requests (first request
pays compile). For throughput serving use ParallelInference, which
micro-batches across callers.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

import numpy as np


class JsonModelServer:
    """Serve a model's `output()` over HTTP JSON.

    `input_adapter` maps the decoded JSON payload to the model input
    (default: np.asarray of `features`, float32); `output_adapter` maps
    the model output to a JSON-serializable object (default: nested
    lists) — mirroring the reference's InferenceAdapter/Serializer seam.
    """

    def __init__(self, model, port: int = 0,
                 input_adapter: Optional[Callable[[dict], Any]] = None,
                 output_adapter: Optional[Callable[[Any], Any]] = None):
        self.model = model
        self._requested_port = port
        self.input_adapter = input_adapter or self._default_input
        self.output_adapter = output_adapter or self._default_output
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._infer_lock = threading.Lock()

    @staticmethod
    def _default_input(payload: dict):
        if "features" not in payload:
            raise ValueError("payload must contain 'features'")
        return np.asarray(payload["features"], np.float32)

    @staticmethod
    def _default_output(out):
        if isinstance(out, (list, tuple)):
            return [np.asarray(getattr(o, "jax", o)).tolist() for o in out]
        return np.asarray(getattr(out, "jax", out)).tolist()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        server = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                     _InferenceHandler)
        server.model_server = self  # type: ignore[attr-defined]
        self._httpd = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(target=server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    # -- inference ------------------------------------------------------
    def predict(self, payload: dict):
        x = self.input_adapter(payload)
        with self._infer_lock:  # model output() mutates rng state
            out = self.model.output(x)
        return self.output_adapter(out)

    def info(self) -> dict:
        m = self.model
        return {
            "model_class": type(m).__name__,
            "num_params": int(m.numParams()) if hasattr(m, "numParams")
            else None,
        }


class _InferenceHandler(BaseHTTPRequestHandler):
    server_version = "DL4JTPUModelServer/1.0"

    def log_message(self, *args):
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ms: JsonModelServer = self.server.model_server  # type: ignore
        if self.path.rstrip("/") == "/v1/serving/info":
            return self._json(ms.info())
        return self._json({"error": "not found"}, 404)

    def do_POST(self):
        ms: JsonModelServer = self.server.model_server  # type: ignore
        if self.path.rstrip("/") != "/v1/serving/predict":
            return self._json({"error": "not found"}, 404)
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            return self._json({"output": ms.predict(payload)})
        except Exception as e:  # bad payload -> 400 with reason
            return self._json({"error": str(e)}, 400)


class JsonRemoteInference:
    """Client for JsonModelServer (reference: JsonRemoteInference)."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def predict(self, features) -> np.ndarray:
        body = json.dumps(
            {"features": np.asarray(features).tolist()}).encode()
        req = urllib.request.Request(
            self.endpoint + "/v1/serving/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            out = json.loads(r.read())
        if "error" in out:
            raise RuntimeError(out["error"])
        return np.asarray(out["output"])


__all__ = ["JsonModelServer", "JsonRemoteInference"]
