"""HTTP JSON inference server + client.

Reference: deeplearning4j-remote — org/deeplearning4j/remote/
JsonModelServer (serves MultiLayerNetwork / ComputationGraph / SameDiff
over HTTP JSON with pluggable serializers) and JsonRemoteInference (the
client), SURVEY.md §2.36.

Endpoints (stdlib http.server, daemon thread):
    POST /v1/serving/predict   {"features": <nested list>, ...}
                               -> {"output": <nested list>}
    POST /v1/serving/generate  {"prompt_ids": [...],
                                "max_new_tokens": N,
                                "temperature": 0.0, "eos_id": opt}
                               -> {"request_id", "tokens": [...],
                                   "ttft_ms": ..., "latency_ms": ...,
                                   "finish_reason", "trace_id"}
    GET  /v1/serving/info      -> model/engine metadata
    GET  /v1/serving/stats     -> live engine stats (occupancy,
                                  queue, KV pages, warm pool, recent
                                  request ids + finish reasons)
    GET  /v1/serving/requests  -> live + recent request-trace
                                  summaries (tracing on)
    GET  /v1/serving/requests/<id>
                               -> ONE request's traced timeline:
                                  queue_wait -> prefill -> decode
                                  bursts -> finish (profiler/tracing)
    GET  /v1/jobs[/<id>]       -> control-plane job statuses (when a
                                  control.JobScheduler is live)
    GET  /v1/programs[?n=N]    -> roofline program registry snapshot,
                                  top-N by device time
    POST /v1/profile           -> forced bounded device-profile
                                  capture ({"duration_s": 0.5}); 409
                                  while a trace/capture is active
    GET  /v1/alerts            -> SLO alert states + rule inventory
                                  (when a profiler.slo.SLOEngine is
                                  live)
    GET  /v1/query             -> PromQL-lite instant query against
                                  the embedded time-series store
                                  (?query=<expr>[&time=t]; 404 with a
                                  hint while DL4J_TPU_TSDB is off)
    GET  /v1/query_range       -> PromQL-lite range query (?query=..
                                  &start=..&end=..&step=..)
    POST /v1/jobs              -> submit via a registered job factory
    POST /v1/jobs/<id>/cancel  -> cancel (train: checkpoint + exit;
         /v1/jobs/<id>/drain      serve: cancel in-flight + shutdown)
    GET  /v1/fleet[/<id>]      -> serve fleets: live replicas, pending
                                  scale ops, queue pressure
    POST /v1/fleet/scale       -> drive a fleet to a target replica
                                  count (elastic grow/shrink)
    GET  /v1/workers[/<w>]     -> fleet failure domains + supervised
                                  worker processes
    POST /v1/workers/<w>/preempt  -> maintenance notice
                                  ({"deadline_s": n}): jobs
                                  checkpoint-and-drain before the kill
    POST /v1/workers/<w>/restore  -> worker capacity back in the pool

Batching note: ``predict`` requests are served one-by-one; the
TPU-side win comes from the jit-compiled forward reused across
requests (first request pays compile). For throughput serving use
ParallelInference (classifier batching across callers) or attach a
continuous-batching DecodeEngine (``JsonModelServer(engine=...)``) —
``generate`` requests from concurrent HTTP clients then share the
engine's fixed-shape decode step, each joining a free slot mid-flight
(docs/SERVING.md).
"""

from __future__ import annotations

import collections
import json
import threading
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

import numpy as np


class JsonModelServer:
    """Serve a model's `output()` over HTTP JSON.

    `input_adapter` maps the decoded JSON payload to the model input
    (default: np.asarray of `features`, float32); `output_adapter` maps
    the model output to a JSON-serializable object (default: nested
    lists) — mirroring the reference's InferenceAdapter/Serializer seam.
    """

    def __init__(self, model=None, port: int = 0,
                 input_adapter: Optional[Callable[[dict], Any]] = None,
                 output_adapter: Optional[Callable[[Any], Any]] = None,
                 engine=None):
        if model is None and engine is None:
            raise ValueError("need a model (predict), an engine "
                             "(generate), or both")
        self.model = model
        self.engine = engine      # serving.DecodeEngine (or None)
        self._requested_port = port
        self.input_adapter = input_adapter or self._default_input
        self.output_adapter = output_adapter or self._default_output
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._infer_lock = threading.Lock()
        # idempotency: key -> the ORIGINAL submitted request handle.
        # A replayed POST (client retried after a connection reset that
        # ate the response) waits on that request instead of
        # re-prefilling and double-generating. Bounded LRU.
        self._idem: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._idem_lock = threading.Lock()

    #: idempotency keys remembered (each holds one finished request
    #: handle — small; old keys fall off the back)
    IDEMPOTENCY_CAPACITY = 1024

    @staticmethod
    def _default_input(payload: dict):
        if "features" not in payload:
            raise ValueError("payload must contain 'features'")
        return np.asarray(payload["features"], np.float32)

    @staticmethod
    def _default_output(out):
        if isinstance(out, (list, tuple)):
            return [np.asarray(getattr(o, "jax", o)).tolist() for o in out]
        return np.asarray(getattr(out, "jax", out)).tolist()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        # metrics-history sampler rides along with the server when
        # DL4J_TPU_TSDB=1 (ensure_default is a no-op otherwise; off
        # mode must not even import the timeseries module)
        import os

        if os.environ.get("DL4J_TPU_TSDB", "0") not in \
                ("0", "", "false"):
            from deeplearning4j_tpu.profiler import timeseries

            timeseries.ensure_default()
        server = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                     _InferenceHandler)
        server.model_server = self  # type: ignore[attr-defined]
        self._httpd = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(target=server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    # -- inference ------------------------------------------------------
    def predict(self, payload: dict):
        if self.model is None:
            raise ValueError("no model attached (generation-only "
                             "server — use /v1/serving/generate)")
        x = self.input_adapter(payload)
        with self._infer_lock:  # model output() mutates rng state
            out = self.model.output(x)
        return self.output_adapter(out)

    def generate(self, payload: dict) -> dict:
        """Continuous-batching generation: submit to the engine and
        block THIS handler thread only — ThreadingHTTPServer runs one
        thread per connection, so concurrent clients' requests decode
        side by side in the engine's slots (no _infer_lock here; the
        engine is the serialization point)."""
        if self.engine is None:
            raise ValueError("no decode engine attached "
                             "(JsonModelServer(engine=...))")
        if "prompt_ids" not in payload:
            raise ValueError("payload must contain 'prompt_ids'")

        def _submit():
            return self.engine.submit(
                # 1-D (or [1, t0]) only — submit() rejects batched
                # arrays rather than silently concatenating sequences
                np.asarray(payload["prompt_ids"], np.int32),
                int(payload.get("max_new_tokens", 16)),
                float(payload.get("temperature", 0.0)),
                payload.get("eos_id"),
                payload.get("sample_seed"),
                session_id=payload.get("session_id"),
                # speculative decoding: None follows the engine's
                # spec_decode config, false opts this request out
                spec_decode=payload.get("spec_decode"))

        # idempotent submit: a replayed POST (the client's connection
        # reset after the server already admitted the request) returns
        # the ORIGINAL request's stream instead of re-prefilling a
        # non-idempotent generation. The get-or-submit is atomic under
        # the lock, so two concurrent replays admit exactly once;
        # capacity rejects are NOT remembered (the retry should re-try
        # admission).
        key = payload.get("idempotency_key")
        replayed = False
        if key is not None:
            key = str(key)
            with self._idem_lock:
                req = self._idem.get(key)
                if req is not None:
                    replayed = True
                    self._idem.move_to_end(key)
                else:
                    req = _submit()
                    self._idem[key] = req
                    while len(self._idem) > self.IDEMPOTENCY_CAPACITY:
                        self._idem.popitem(last=False)
        else:
            req = _submit()
        tokens = req.result(timeout=float(payload.get("timeout", 300)))
        out = {
            # request_id joins client logs against the server-side
            # trace (GET /v1/serving/requests/<request_id>)
            "request_id": req.request_id,
            "trace_id": req.trace_id,
            "tokens": np.asarray(tokens).tolist(),
            "finish_reason": req.finish_reason,
            # prompt tokens served from cached KV (prefix cache /
            # sticky session) instead of prefill compute; session_id
            # echoes the sticky-session key the server pinned under
            "cache_hit_tokens": req.cache_hit_tokens,
            "session_id": req.session_id,
            # per-replica tag: which engine served this request (an
            # engine's engine_id, or the FINAL replica under a fleet)
            "engine": getattr(req, "engine_id", None),
            "ttft_ms": round(req.ttft_s * 1e3, 3)
            if req.ttft_s is not None else None,
            "latency_ms": round(req.latency_s * 1e3, 3)
            if req.latency_s is not None else None,
        }
        # fleet requests also carry the routing decision (replica,
        # reason=affinity|score|..., lane, attempts incl. failovers)
        routing = getattr(req, "routing", None)
        if routing:
            out["routing"] = dict(routing)
        # speculative-decoding acceptance stats (engines with
        # spec_decode on): how many draft tokens the target accepted
        # for THIS request — 0 proposed means the request never rode a
        # verify dispatch (spec off, or opted out)
        proposed = getattr(req, "spec_proposed", 0)
        if proposed:
            accepted = getattr(req, "spec_accepted", 0)
            out["spec"] = {"proposed": proposed, "accepted": accepted,
                           "acceptance": round(accepted / proposed, 4)}
        if replayed:
            out["replayed"] = True
        return out

    def info(self) -> dict:
        m = self.model
        out = {
            "model_class": type(m).__name__ if m is not None else None,
            "num_params": int(m.numParams())
            if hasattr(m, "numParams") else None,
        }
        if self.engine is not None:
            st = self.engine.stats()
            out["engine"] = {k: st[k] for k in
                             ("slots", "page_size", "max_context",
                              "quantization", "prefill_buckets")}
        return out


class _InferenceHandler(BaseHTTPRequestHandler):
    server_version = "DL4JTPUModelServer/1.0"

    def log_message(self, *args):
        pass

    def _json(self, obj, code=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ms: JsonModelServer = self.server.model_server  # type: ignore
        path = self.path.rstrip("/")
        if path == "/v1/serving/info":
            return self._json(ms.info())
        if path == "/v1/serving/stats":
            if ms.engine is None:
                return self._json({"error": "no decode engine"}, 404)
            return self._json(ms.engine.stats())
        if path == "/v1/serving/prefix_cache":
            if ms.engine is None:
                return self._json({"error": "no decode engine"}, 404)
            return self._json(ms.engine.prefix_stats())
        if path == "/v1/serving/requests":
            from deeplearning4j_tpu.profiler import tracing

            return self._json({
                "tracing_enabled": tracing.enabled(),
                "live": tracing.live_summaries(),
                "recent": tracing.recent_summaries(),
            })
        if path.startswith("/v1/serving/requests/"):
            from deeplearning4j_tpu.profiler import tracing

            rid = path.rsplit("/", 1)[1]
            tl = tracing.timeline(rid)
            if tl is None:
                hint = ("" if tracing.enabled() else
                        " (tracing is off — set DL4J_TPU_TRACING=1 or "
                        "tracing.set_enabled(True) before submitting)")
                return self._json(
                    {"error": f"no timeline for request {rid}{hint}"},
                    404)
            return self._json(tl)
        if path == "/v1/jobs" or path.startswith("/v1/jobs/"):
            from deeplearning4j_tpu import control

            obj, code = control.http_jobs_get(path)
            return self._json(obj, code)
        if path == "/v1/workers" or path.startswith("/v1/workers/"):
            from deeplearning4j_tpu import control

            obj, code = control.http_workers_get(path)
            return self._json(obj, code)
        if path == "/v1/fleet" or path.startswith("/v1/fleet/"):
            from deeplearning4j_tpu import control

            obj, code = control.http_fleet_get(path)
            return self._json(obj, code)
        if path == "/v1/alerts":
            from deeplearning4j_tpu.profiler import slo

            obj, code = slo.http_alerts()
            return self._json(obj, code)
        if path == "/v1/programs" or path.startswith("/v1/programs?"):
            # path keeps the query string here (only the trailing "/"
            # is stripped) — split it off for the handler
            from deeplearning4j_tpu.profiler import programs

            obj, code = programs.http_programs(path.partition("?")[2])
            return self._json(obj, code)
        if path == "/v1/query" or path.startswith("/v1/query?"):
            from deeplearning4j_tpu.profiler import timeseries

            obj, code = timeseries.http_query(path.partition("?")[2])
            return self._json(obj, code)
        if path == "/v1/query_range" \
                or path.startswith("/v1/query_range?"):
            from deeplearning4j_tpu.profiler import timeseries

            obj, code = timeseries.http_query_range(
                path.partition("?")[2])
            return self._json(obj, code)
        return self._json({"error": "not found"}, 404)

    def do_POST(self):
        ms: JsonModelServer = self.server.model_server  # type: ignore
        path = self.path.rstrip("/")
        if path == "/v1/jobs" or path.startswith("/v1/jobs/") \
                or path.startswith("/v1/workers/") \
                or path.startswith("/v1/fleet/"):
            from deeplearning4j_tpu import control

            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except Exception as e:
                return self._json({"error": str(e)}, 400)
            if path.startswith("/v1/workers/"):
                obj, code = control.http_workers_post(path, payload)
            elif path.startswith("/v1/fleet/"):
                obj, code = control.http_fleet_post(path, payload)
            else:
                obj, code = control.http_jobs_post(path, payload)
            return self._json(obj, code)
        if path == "/v1/profile":
            # forced device-profile capture (profiler/programs.py)
            from deeplearning4j_tpu.profiler import programs

            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except Exception as e:
                return self._json({"error": str(e)}, 400)
            obj, code = programs.http_profile(payload)
            return self._json(obj, code)
        if path not in ("/v1/serving/predict", "/v1/serving/generate"):
            return self._json({"error": "not found"}, 404)
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            if path == "/v1/serving/generate":
                return self._json(ms.generate(payload))
            return self._json({"output": ms.predict(payload)})
        except Exception as e:
            # hard capacity reject (CapacityRejected, duck-typed so
            # this module stays serving-agnostic): a STRUCTURED 429
            # with Retry-After, not an opaque 400 — clients back off
            # for the engine's measured hint instead of guessing
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                return self._json(
                    {"error": str(e), "code": 429,
                     "retry_after_s": retry_after},
                    429, headers={"Retry-After":
                                  f"{max(retry_after, 0.0):.3f}"})
            return self._json({"error": str(e)}, 400)


class JsonRemoteInference:
    """Client for JsonModelServer (reference: JsonRemoteInference).

    ``generate``/``generate_full`` retry with bounded backoff on the
    server's structured 429 capacity reject (honoring its
    ``retry_after_s`` hint) and on connection resets — a full queue or
    a briefly-restarting replica surfaces as a short wait, not a raw
    exception at the caller. ``retries=0`` restores fail-fast.

    Connection-reset retries are EXACTLY-ONCE against one server
    process: every ``generate``/``generate_full`` call mints a client-
    side ``idempotency_key``, and a replayed POST returns the ORIGINAL
    request's result instead of re-prefilling a non-idempotent
    generation (the server remembers the newest 1024 keys; the
    response carries ``replayed: true``). A replay against a
    *restarted* server process is a fresh submit — pass a
    ``sample_seed`` if sampled retries must also reproduce there."""

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 retries: int = 4, max_backoff_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self.retries = int(retries)
        self.max_backoff_s = float(max_backoff_s)

    def predict(self, features) -> np.ndarray:
        out = self._post("/v1/serving/predict",
                         {"features": np.asarray(features).tolist()})
        return np.asarray(out["output"])

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, eos_id=None,
                 session_id=None) -> np.ndarray:
        """Continuous-batching generation via the server's decode
        engine; returns the generated token ids. ``session_id`` makes
        the turn sticky: the server pins its KV pages under that id,
        and the next call whose prompt extends this conversation
        resumes without re-prefilling the history."""
        out = self.generate_full(prompt_ids, max_new_tokens,
                                 temperature, eos_id, session_id)
        return np.asarray(out["tokens"], np.int32)

    def generate_full(self, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, eos_id=None,
                      session_id=None) -> dict:
        """Like generate() but returns the whole response dict
        (request_id, finish_reason, cache_hit_tokens, timings)."""
        payload = {
            "prompt_ids": np.asarray(prompt_ids,
                                     np.int32).reshape(-1).tolist(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_id": eos_id,
            # one key per LOGICAL request, shared by every retry of it:
            # a POST replayed after a connection reset joins the
            # original submission instead of double-generating
            "idempotency_key": uuid.uuid4().hex,
        }
        if session_id is not None:
            payload["session_id"] = session_id
        return self._post_with_retry("/v1/serving/generate", payload)

    def prefix_cache_stats(self) -> dict:
        """GET /v1/serving/prefix_cache — cross-request KV-reuse
        stats (hit/miss counters, cached/shared/pinned pages)."""
        req = urllib.request.Request(
            self.endpoint + "/v1/serving/prefix_cache")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def _post(self, path: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.endpoint + path, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            out = json.loads(r.read())
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def _post_with_retry(self, path: str, payload: dict) -> dict:
        """Bounded retry-with-backoff around _post: a 429 waits the
        server's retry_after_s hint (capped), a connection reset waits
        a doubling backoff; anything else — and exhaustion — raises."""
        import http.client
        import time as _time
        import urllib.error

        backoff = 0.05
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return self._post(path, payload)
            except urllib.error.HTTPError as e:
                if e.code != 429:
                    raise
                try:
                    info = json.loads(e.read() or b"{}")
                except Exception:
                    info = {}
                wait = min(float(info.get("retry_after_s", backoff)),
                           self.max_backoff_s)
                last = RuntimeError(
                    f"server at capacity (429): "
                    f"{info.get('error', e.reason)}")
            except (ConnectionResetError, ConnectionRefusedError,
                    http.client.RemoteDisconnected) as e:
                wait, last = min(backoff, self.max_backoff_s), e
            except urllib.error.URLError as e:
                if not isinstance(e.reason, (ConnectionResetError,
                                             ConnectionRefusedError)):
                    raise
                wait, last = min(backoff, self.max_backoff_s), e
            if attempt == self.retries:
                break
            _time.sleep(wait)
            backoff = min(backoff * 2, self.max_backoff_s)
        raise RuntimeError(
            f"generate failed after {self.retries + 1} attempts: "
            f"{last}")


__all__ = ["JsonModelServer", "JsonRemoteInference"]
