"""Remote JSON inference (reference: deeplearning4j-remote —
JsonModelServer / JsonRemoteInference, SURVEY.md §2.36)."""

from deeplearning4j_tpu.remote.server import JsonModelServer, JsonRemoteInference

__all__ = ["JsonModelServer", "JsonRemoteInference"]
