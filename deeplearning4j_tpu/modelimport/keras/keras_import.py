"""Keras HDF5 → MultiLayerNetwork / ComputationGraph.

Reference: KerasModelImport.importKerasSequentialModelAndWeights /
importKerasModelAndWeights (deeplearning4j-modelimport). Supports the
Keras-3 HDF5 layout (``model_config`` JSON attr + ``model_weights``
group with per-layer ``weight_names``), which is what tf.keras ≥2.16
writes for ``model.save("*.h5")``.

Layer coverage mirrors the reference's ~60 mappers: Dense, Conv1D/2D/3D,
Conv2DTranspose, SeparableConv2D, DepthwiseConv2D, LocallyConnected1D/2D,
Max/AveragePooling1D/2D/3D, GlobalMax/AveragePooling1D/2D, Flatten,
Dropout (+Alpha/Gaussian/Spatial/Noise), BatchNormalization,
LayerNormalization, Activation, ReLU/Softmax/LeakyReLU/ELU/
ThresholdedReLU/PReLU, ZeroPadding/Cropping/UpSampling 1D/2D/3D,
Permute, Reshape, RepeatVector, Masking, Embedding, LSTM, GRU,
SimpleRNN, Bidirectional (all merge modes), TimeDistributed(Dense),
Add/Subtract/Multiply/Average/Maximum/Minimum/Concatenate (functional
graphs).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.builder import (MultiLayerConfiguration,
                                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               Bidirectional,
                                               ConvolutionLayer, DenseLayer,
                                               DropoutLayer, EmbeddingLayer,
                                               EmbeddingSequenceLayer,
                                               FlattenLayer,
                                               GlobalPoolingLayer,
                                               LastTimeStep, LSTM,
                                               OutputLayer,
                                               SeparableConvolution2D,
                                               SimpleRnn, SubsamplingLayer,
                                               Upsampling2D, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.layers import LayerNormalization
from deeplearning4j_tpu.nn.conf.layers_extra import (
    Convolution1D, Convolution3D, Cropping1D, Cropping2D, Cropping3D,
    Deconvolution2D, DepthwiseConvolution2D, GRU, LocallyConnected1D,
    LocallyConnected2D, MaskLayer, PermuteLayer, PReLULayer,
    RepeatVector, ReshapeLayer, Subsampling1DLayer, Subsampling3DLayer,
    Upsampling1D, Upsampling3D, ZeroPadding1DLayer, ZeroPadding3DLayer,
)
from deeplearning4j_tpu.nn.conf.dropout import (
    AlphaDropout, GaussianDropout, GaussianNoise, SpatialDropout,
)
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (ElementWiseVertex,
                                                  LayerVertex, MergeVertex,
                                                  PreprocessorVertex)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork


class InvalidKerasConfigurationException(ValueError):
    """reference: exceptions.InvalidKerasConfigurationException."""


class UnsupportedKerasConfigurationException(ValueError):
    """reference: exceptions.UnsupportedKerasConfigurationException."""


_ACT_MAP = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign", "swish": "swish",
    "silu": "swish", "gelu": "gelu", "hard_sigmoid": "hardsigmoid",
    "relu6": "relu6", "mish": "mish",
}


def _map_activation(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):  # serialized Activation object
        name = name.get("config", {}).get("name", "linear")
    try:
        return _ACT_MAP[name]
    except KeyError:
        raise UnsupportedKerasConfigurationException(
            f"unsupported Keras activation {name!r}") from None


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return (int(v[0]), int(v[1]))


def _conv_mode(padding: str) -> str:
    return "Same" if padding == "same" else "Truncate"


def _input_type_from_shape(shape) -> InputType:
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feedForward(int(dims[0]))
    if len(dims) == 2:
        t = int(dims[0]) if dims[0] is not None else -1
        return InputType.recurrent(int(dims[1]), t)
    if len(dims) == 3:
        return InputType.convolutional(int(dims[0]), int(dims[1]),
                                       int(dims[2]))
    if len(dims) == 4:
        return InputType.convolutional3D(int(dims[0]), int(dims[1]),
                                         int(dims[2]), int(dims[3]))
    raise UnsupportedKerasConfigurationException(
        f"unsupported input shape {shape}")


def _check_channels_last(cfg: dict, name: str) -> None:
    df = cfg.get("data_format", "channels_last")
    if df != "channels_last":
        raise UnsupportedKerasConfigurationException(
            f"layer {name!r}: data_format={df!r}; only channels_last "
            "(NHWC — the TPU-native layout) is supported")


#: user-registered mappers: class_name -> fn(cfg: dict) -> Layer
#: (reference: KerasLayer.registerCustomLayer / registerLambdaLayer —
#: the escape hatch for custom layers and Lambda layers, whose Keras
#: serialization carries no portable function body)
_CUSTOM_LAYER_MAPPERS: Dict[str, Any] = {}


def registerCustomLayer(class_name: str, mapper) -> None:
    """Register a mapper for a Keras layer class this importer doesn't
    know (incl. "Lambda" — register a mapper that returns a layer
    implementing the lambda's computation). ``mapper(cfg)`` receives
    the layer's Keras config dict and returns a framework Layer.
    Consulted only AFTER the built-in mappers (reference semantics:
    custom mappers extend the registry, they cannot shadow built-ins)."""
    _CUSTOM_LAYER_MAPPERS[class_name] = mapper


def unregisterCustomLayer(class_name: str) -> None:
    """Remove a previously registered custom mapper (no-op if absent)."""
    _CUSTOM_LAYER_MAPPERS.pop(class_name, None)


def _map_layer(class_name: str, cfg: dict, is_last: bool):
    """Keras layer config → (our Layer | 'flatten' | None).

    None = structural no-op (InputLayer, Reshape handled elsewhere).
    Successful dispatch records into the mapper-execution accounting
    (tests/test_zzz_mapper_execution_gate.py) — same OpValidation role
    as the op registry's executed-op set.
    """
    out = _map_layer_impl(class_name, cfg, is_last)
    from deeplearning4j_tpu.modelimport import trace as mapper_trace
    mapper_trace.record("keras", class_name)
    return out


def supported_layer_names():
    """The registered Keras mapper set, derived MECHANICALLY from
    _map_layer_impl's dispatch chain (AST walk over `class_name`
    comparisons) so the gate's registered list cannot drift from the
    code. TimeDistributed's inner 'Dense' remap and custom layers are
    covered by the same chain."""
    import ast
    import inspect
    import textwrap

    src = textwrap.dedent(inspect.getsource(_map_layer_impl))
    names = set()
    for node in ast.walk(ast.parse(src)):
        if (isinstance(node, ast.Compare)
                and isinstance(node.left, ast.Name)
                and node.left.id == "class_name"):
            for comp in node.comparators:
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    names.add(comp.value)
                elif isinstance(comp, (ast.Tuple, ast.List)):
                    names.update(
                        e.value for e in comp.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
    return sorted(names)


def _map_layer_impl(class_name: str, cfg: dict, is_last: bool):
    name = cfg.get("name", class_name)
    # (InputLayer never reaches here — both import paths consume it as
    # the input-type declaration before layer mapping)
    if class_name == "Flatten":
        return FlattenLayer(name=name)
    if class_name == "Dense":
        act = _map_activation(cfg.get("activation"))
        if is_last:
            loss = {"softmax": "mcxent", "sigmoid": "xent"}.get(act, "mse")
            return OutputLayer(name=name, n_out=cfg["units"], activation=act,
                               loss=loss, has_bias=cfg.get("use_bias", True))
        return DenseLayer(name=name, n_out=cfg["units"], activation=act,
                          has_bias=cfg.get("use_bias", True))
    if class_name == "Conv2D":
        _check_channels_last(cfg, name)
        return ConvolutionLayer(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name == "SeparableConv2D":
        _check_channels_last(cfg, name)
        return SeparableConvolution2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        _check_channels_last(cfg, name)
        return SubsamplingLayer(
            name=name,
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(
            name=name,
            pooling_type="max" if "Max" in class_name else "avg")
    if class_name == "Dropout":
        return DropoutLayer(name=name, rate=float(cfg.get("rate", 0.5)))
    if class_name == "BatchNormalization":
        return BatchNormalization(
            name=name, eps=float(cfg.get("epsilon", 1e-3)),
            decay=float(cfg.get("momentum", 0.99)))
    if class_name == "Activation":
        return ActivationLayer(
            name=name, activation=_map_activation(cfg.get("activation")))
    if class_name == "ReLU":
        return ActivationLayer(name=name, activation="relu")
    if class_name == "Softmax":
        return ActivationLayer(name=name, activation="softmax")
    if class_name == "LeakyReLU":
        slope = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return ActivationLayer(name=name, activation="leakyrelu",
                               alpha=float(slope))
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", 1)
        if isinstance(pad, (list, tuple)) and isinstance(pad[0],
                                                         (list, tuple)):
            if pad[0][0] != pad[0][1] or pad[1][0] != pad[1][1]:
                raise UnsupportedKerasConfigurationException(
                    f"asymmetric ZeroPadding2D {pad} unsupported")
            pad = (pad[0][0], pad[1][0])
        return ZeroPaddingLayer(name=name, pad=_pair(pad))
    if class_name == "UpSampling2D":
        size = cfg.get("size", 2)
        if isinstance(size, (list, tuple)):
            if len(set(size)) != 1:
                raise UnsupportedKerasConfigurationException(
                    f"UpSampling2D {name!r}: anisotropic size {size} "
                    "unsupported")
            size = size[0]
        return Upsampling2D(name=name, size=int(size))
    if class_name == "Embedding":
        return EmbeddingSequenceLayer(name=name, n_in=cfg["input_dim"],
                                      n_out=cfg["output_dim"])
    if class_name == "LSTM":
        if _map_activation(cfg.get("activation", "tanh")) != "tanh" or \
                _map_activation(cfg.get("recurrent_activation",
                                        "sigmoid")) != "sigmoid":
            raise UnsupportedKerasConfigurationException(
                f"LSTM {name!r}: only tanh/sigmoid cell activations map "
                "onto the fused cell")
        lstm = LSTM(name=name, n_out=cfg["units"],
                    forget_gate_bias_init=1.0
                    if cfg.get("unit_forget_bias", True) else 0.0)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, underlying=lstm)
        return lstm
    if class_name == "SimpleRNN":
        rnn = SimpleRnn(name=name, n_out=cfg["units"])
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, underlying=rnn)
        return rnn
    if class_name == "GRU":
        if not cfg.get("reset_after", True):
            raise UnsupportedKerasConfigurationException(
                f"GRU {name!r}: reset_after=False applies the reset gate "
                "before the recurrent matmul — not representable in the "
                "fused reset-after cell")
        if _map_activation(cfg.get("activation", "tanh")) != "tanh" or \
                _map_activation(cfg.get("recurrent_activation",
                                        "sigmoid")) != "sigmoid":
            raise UnsupportedKerasConfigurationException(
                f"GRU {name!r}: only tanh/sigmoid cell activations map "
                "onto the fused cell")
        gru = GRU(name=name, n_out=cfg["units"], recurrent_bias=True)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, underlying=gru)
        return gru
    if class_name == "Conv1D":
        _check_channels_last(cfg, name)
        if cfg.get("padding") == "causal":
            raise UnsupportedKerasConfigurationException(
                f"Conv1D {name!r}: padding='causal' unsupported (would "
                "silently run valid convolution)")
        k = cfg["kernel_size"]
        s = cfg.get("strides", 1)
        d = cfg.get("dilation_rate", 1)
        return Convolution1D(
            name=name, n_out=cfg["filters"],
            kernel_size=int(k[0] if isinstance(k, (list, tuple)) else k),
            stride=int(s[0] if isinstance(s, (list, tuple)) else s),
            dilation=int(d[0] if isinstance(d, (list, tuple)) else d),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name == "Conv3D":
        _check_channels_last(cfg, name)
        return Convolution3D(
            name=name, n_out=cfg["filters"],
            kernel_size=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            dilation=tuple(cfg.get("dilation_rate", (1, 1, 1))),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name == "Conv2DTranspose":
        _check_channels_last(cfg, name)
        d = cfg.get("dilation_rate", 1)
        if _pair(d) != (1, 1):
            raise UnsupportedKerasConfigurationException(
                f"Conv2DTranspose {name!r}: dilation_rate={d} unsupported")
        op = cfg.get("output_padding")
        if op not in (None, 0, (0, 0), [0, 0]):
            raise UnsupportedKerasConfigurationException(
                f"Conv2DTranspose {name!r}: output_padding={op} unsupported")
        return Deconvolution2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name == "DepthwiseConv2D":
        _check_channels_last(cfg, name)
        return DepthwiseConvolution2D(
            name=name, depth_multiplier=cfg.get("depth_multiplier", 1),
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        _check_channels_last(cfg, name)
        k = cfg.get("pool_size", 2)
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = cfg.get("strides") or k
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        return Subsampling1DLayer(
            name=name,
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=k, stride=s,
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        _check_channels_last(cfg, name)
        k = tuple(cfg.get("pool_size", (2, 2, 2)))
        return Subsampling3DLayer(
            name=name,
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=k, stride=tuple(cfg.get("strides") or k),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    if class_name == "UpSampling1D":
        return Upsampling1D(name=name, size=int(cfg.get("size", 2)))
    if class_name == "UpSampling3D":
        size = cfg.get("size", 2)
        if isinstance(size, (list, tuple)):
            if len(set(size)) != 1:
                raise UnsupportedKerasConfigurationException(
                    f"UpSampling3D {name!r}: anisotropic size {size} "
                    "unsupported")
            size = size[0]
        return Upsampling3D(name=name, size=int(size))
    if class_name == "ZeroPadding1D":
        pad = cfg.get("padding", 1)
        pad = tuple(pad) if isinstance(pad, (list, tuple)) else (pad, pad)
        return ZeroPadding1DLayer(name=name, pad=pad)
    if class_name == "ZeroPadding3D":
        pad = cfg.get("padding", 1)
        if isinstance(pad, (list, tuple)):
            if isinstance(pad[0], (list, tuple)):
                if any(p[0] != p[1] for p in pad):
                    raise UnsupportedKerasConfigurationException(
                        f"asymmetric ZeroPadding3D {pad} unsupported")
                pad = tuple(p[0] for p in pad)
            else:
                pad = tuple(pad)
        return ZeroPadding3DLayer(name=name, pad=pad)
    if class_name == "Cropping1D":
        c = cfg.get("cropping", (0, 0))
        c = tuple(c) if isinstance(c, (list, tuple)) else (c, c)
        return Cropping1D(name=name, crop=c)
    if class_name == "Cropping2D":
        c = cfg.get("cropping", ((0, 0), (0, 0)))
        if isinstance(c, int):
            c = (c, c, c, c)
        elif isinstance(c[0], (list, tuple)):
            c = (c[0][0], c[0][1], c[1][0], c[1][1])
        else:
            c = (c[0], c[0], c[1], c[1])
        return Cropping2D(name=name, crop=tuple(int(v) for v in c))
    if class_name == "Cropping3D":
        c = cfg.get("cropping", ((0, 0),) * 3)
        if isinstance(c, int):
            c = (c,) * 6
        elif isinstance(c[0], (list, tuple)):
            c = (c[0][0], c[0][1], c[1][0], c[1][1], c[2][0], c[2][1])
        else:
            c = (c[0], c[0], c[1], c[1], c[2], c[2])
        return Cropping3D(name=name, crop=tuple(int(v) for v in c))
    if class_name in ("LocallyConnected1D", "LocallyConnected2D"):
        if class_name.endswith("1D"):
            _check_channels_last(cfg, name)
            k = cfg["kernel_size"]
            s = cfg.get("strides", 1)
            return LocallyConnected1D(
                name=name, n_out=cfg["filters"],
                kernel_size=int(k[0] if isinstance(k, (list, tuple)) else k),
                stride=int(s[0] if isinstance(s, (list, tuple)) else s),
                activation=_map_activation(cfg.get("activation")),
                has_bias=cfg.get("use_bias", True))
        _check_channels_last(cfg, name)
        return LocallyConnected2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            activation=_map_activation(cfg.get("activation")),
            has_bias=cfg.get("use_bias", True))
    if class_name == "ELU":
        return ActivationLayer(name=name, activation="elu",
                               alpha=float(cfg.get("alpha", 1.0)))
    if class_name == "ThresholdedReLU":
        # arbitrary theta rides ActivationLayer.alpha (the shared
        # parameterized-activation slot)
        return ActivationLayer(name=name, activation="thresholdedrelu",
                               alpha=float(cfg.get("theta", 1.0)))
    if class_name == "Permute":
        return PermuteLayer(name=name,
                            dims=tuple(int(d) for d in cfg["dims"]))
    if class_name == "Reshape":
        return ReshapeLayer(name=name, target_shape=tuple(
            int(d) for d in cfg["target_shape"]))
    if class_name == "TimeDistributed":
        # our Dense already broadcasts over leading axes, which is
        # exactly TimeDistributed(Dense) semantics
        inner = cfg["layer"]
        if inner["class_name"] != "Dense":
            raise UnsupportedKerasConfigurationException(
                f"layer {name!r}: TimeDistributed supports Dense only; "
                f"got {inner['class_name']}")
        mapped = _map_layer("Dense", dict(inner["config"], name=name),
                            is_last=is_last)
        mapped.name = name
        return mapped
    if class_name == "Bidirectional":
        inner = cfg["layer"]
        ret_seq = bool(inner.get("config", {}).get("return_sequences",
                                                   False))
        # map the wrapped layer as sequence-returning; the LAST-STEP
        # rule (fwd t=T-1 merged with bwd t=0) lives in Bidirectional
        # itself via return_sequences=False
        inner_cfg = dict(inner["config"], return_sequences=True)
        wrapped = _map_layer(inner["class_name"], inner_cfg,
                             is_last=False)
        mode = {"concat": "CONCAT", "sum": "ADD", "mul": "MUL",
                "ave": "AVERAGE"}.get(cfg.get("merge_mode", "concat"))
        if mode is None:
            raise UnsupportedKerasConfigurationException(
                f"layer {name!r}: merge_mode="
                f"{cfg.get('merge_mode')!r} not supported")
        return Bidirectional(name=name, layer=wrapped, mode=mode,
                             return_sequences=ret_seq)
    if class_name == "PReLU":
        return PReLULayer(name=name)
    if class_name == "RepeatVector":
        return RepeatVector(name=name, n=int(cfg["n"]))
    if class_name == "Masking":
        return MaskLayer(name=name)
    if class_name == "LayerNormalization":
        return LayerNormalization(name=name,
                                  eps=float(cfg.get("epsilon", 1e-3)))
    if class_name in ("SpatialDropout1D", "SpatialDropout2D",
                      "SpatialDropout3D"):
        return DropoutLayer(name=name,
                            rate=SpatialDropout(float(cfg.get("rate", 0.5))))
    if class_name == "GaussianDropout":
        return DropoutLayer(name=name,
                            rate=GaussianDropout(float(cfg.get("rate", 0.5))))
    if class_name == "GaussianNoise":
        return DropoutLayer(name=name,
                            rate=GaussianNoise(float(cfg.get("stddev", 0.1))))
    if class_name == "AlphaDropout":
        return DropoutLayer(name=name,
                            rate=AlphaDropout(float(cfg.get("rate", 0.5))))
    if class_name in _CUSTOM_LAYER_MAPPERS:
        return _CUSTOM_LAYER_MAPPERS[class_name](cfg)
    raise UnsupportedKerasConfigurationException(
        f"no mapper for Keras layer {class_name!r} — for custom or "
        "Lambda layers, registerCustomLayer(class_name, mapper) "
        "(reference parity: KerasLayer.registerCustomLayer)")


# --------------------------------------------------------------- weights
def _read_layer_weights(mw, layer_name: str) -> Dict[str, np.ndarray]:
    """{short_name: array} for one Keras layer from model_weights."""
    if layer_name not in mw:
        return {}
    g = mw[layer_name]
    names = [n.decode() if isinstance(n, bytes) else n
             for n in g.attrs.get("weight_names", [])]
    shorts = []
    for n in names:
        short = n.split("/")[-1]
        if short.endswith(":0"):
            short = short[:-2]
        shorts.append(short)
    dup = {s_ for s_ in shorts if shorts.count(s_) > 1}
    out: Dict[str, np.ndarray] = {}
    for n, short in zip(names, shorts):
        if short in dup:
            # path-qualify duplicates (Bidirectional's forward/backward
            # cells both end in kernel/recurrent_kernel/bias)
            marker = f"/{layer_name}/"
            rel = n.split(marker, 1)[1] if marker in n else n
            if rel.endswith(":0"):
                rel = rel[:-2]
            out[rel] = np.asarray(g[n])
        else:
            out[short] = np.asarray(g[n])
    return out


def _assign_params(layer, params: dict, state: dict,
                   kw: Dict[str, np.ndarray], lname: str) -> None:
    """Copy Keras weights into our param/state dicts (shapes asserted)."""

    def put(dst: dict, key: str, arr: np.ndarray):
        if key not in dst:
            raise InvalidKerasConfigurationException(
                f"layer {lname!r}: no target param {key!r}")
        if tuple(dst[key].shape) != tuple(arr.shape):
            raise InvalidKerasConfigurationException(
                f"layer {lname!r} param {key!r}: shape "
                f"{arr.shape} vs expected {tuple(dst[key].shape)}")
        dst[key] = jnp.asarray(arr, dtype=dst[key].dtype)

    if isinstance(layer, LastTimeStep):
        layer = layer.underlying
    if isinstance(layer, SeparableConvolution2D):
        if "depthwise_kernel" in kw:
            put(params, "dW", kw["depthwise_kernel"])
        if "pointwise_kernel" in kw:
            put(params, "pW", kw["pointwise_kernel"])
        if "bias" in kw:
            put(params, "b", kw["bias"])
        return
    if isinstance(layer, BatchNormalization):
        if "gamma" in kw:
            put(params, "gamma", kw["gamma"])
        if "beta" in kw:
            put(params, "beta", kw["beta"])
        if "moving_mean" in kw:
            put(state, "mean", kw["moving_mean"])
        if "moving_variance" in kw:
            put(state, "var", kw["moving_variance"])
        return
    if isinstance(layer, Bidirectional):
        # classify by PATH SEGMENT: Keras names the wrapped cells
        # forward_<inner>/... and backward_<inner>/...; matching on a
        # bare substring would misroute when the user layer name itself
        # contains "forward"
        fwd: Dict[str, np.ndarray] = {}
        bwd: Dict[str, np.ndarray] = {}
        for k, v in kw.items():
            segs = k.split("/")
            short = segs[-1]
            is_f = any(s_.startswith("forward") for s_ in segs[:-1])
            is_b = any(s_.startswith("backward") for s_ in segs[:-1])
            if is_f == is_b:
                raise InvalidKerasConfigurationException(
                    f"layer {lname!r}: cannot attribute weight {k!r} "
                    "to the forward or backward cell")
            (fwd if is_f else bwd)[short] = v
        _assign_params(layer.layer, params["fw"], {}, fwd, lname + "/fw")
        _assign_params(layer.layer, params["bw"], {}, bwd, lname + "/bw")
        return
    if isinstance(layer, (LSTM, SimpleRnn)):
        # Keras LSTM kernel (in,4h) gate order i,f,c,o == our i,f,g,o
        if "kernel" in kw:
            put(params, "W", kw["kernel"])
        if "recurrent_kernel" in kw:
            put(params, "RW", kw["recurrent_kernel"])
        if "bias" in kw:
            put(params, "b", kw["bias"])
        return
    if isinstance(layer, GRU):
        # Keras gate order z,r,h -> ours r,z,n (block permutation)
        def perm(a):
            h = a.shape[-1] // 3
            z, r, n = a[..., :h], a[..., h:2 * h], a[..., 2 * h:]
            return np.concatenate([r, z, n], axis=-1)
        if "kernel" in kw:
            put(params, "W", perm(kw["kernel"]))
        if "recurrent_kernel" in kw:
            put(params, "RW", perm(kw["recurrent_kernel"]))
        if "bias" in kw:
            b = kw["bias"]
            if b.ndim == 2:   # reset_after: [2, 3h] = (input, recurrent)
                put(params, "b", perm(b[0]))
                put(params, "Rb", perm(b[1]))
            else:
                put(params, "b", perm(b))
        return
    if isinstance(layer, Deconvolution2D):
        # Keras Conv2DTranspose kernel is (kh,kw,out,in) with
        # gradient-of-conv semantics; ours is HWIO correlation, so
        # transpose to (kh,kw,in,out) AND flip the spatial dims
        # (verified numerically against tf.nn.conv2d_transpose)
        if "kernel" in kw:
            put(params, "W",
                np.transpose(kw["kernel"], (0, 1, 3, 2))[::-1, ::-1].copy())
        if "bias" in kw:
            put(params, "b", kw["bias"])
        return
    if isinstance(layer, DepthwiseConvolution2D):
        # Keras 2 names it depthwise_kernel, Keras 3 plain kernel
        dk = kw.get("depthwise_kernel", kw.get("kernel"))
        if dk is not None:
            put(params, "W", dk)
        if "bias" in kw:
            put(params, "b", kw["bias"])
        return
    if isinstance(layer, (LocallyConnected1D, LocallyConnected2D)):
        # Keras flattens each patch feature-axis as (kH,kW,C) row-major;
        # our locally_connected* ops consume conv_general_dilated_patches
        # output, which is channel-major (C,kH,kW). Permute the middle
        # axis accordingly (verified vs a numpy Keras-semantics model in
        # tests/test_keras_import.py::test_locally_connected_*).
        if "kernel" in kw:
            k = kw["kernel"]
            if isinstance(layer, LocallyConnected2D):
                kh, kkw = layer.kernel_size
            else:
                kh, kkw = layer.kernel_size, 1
            p, kc, f = k.shape
            c_in = kc // (kh * kkw)
            k = (k.reshape(p, kh * kkw, c_in, f)
                 .transpose(0, 2, 1, 3).reshape(p, kc, f))
            put(params, "W", k)
        if "bias" in kw:
            b = kw["bias"]
            # Keras LC bias is per-position ((oh,ow,f) / (oT,f)) and so
            # is ours; a trained file may still carry a flat (f,) bias
            # (use_bias with implementation quirks) — broadcast it.
            if "b" in params and tuple(params["b"].shape) != tuple(b.shape):
                b = np.broadcast_to(b, params["b"].shape)
            put(params, "b", b)
        return
    if isinstance(layer, PReLULayer):
        if "alpha" in kw:
            a = kw["alpha"]
            put(params, "alpha", a.reshape(-1))
        return
    if isinstance(layer, LayerNormalization):
        if "gamma" in kw:
            put(params, "gamma", kw["gamma"])
        if "beta" in kw:
            put(params, "beta", kw["beta"])
        return
    if isinstance(layer, EmbeddingLayer):
        if "embeddings" in kw:
            put(params, "W", kw["embeddings"])
        return
    # dense / conv (HWIO == our conv layout; (in,out) == our dense)
    if "kernel" in kw:
        put(params, "W", kw["kernel"])
    if "bias" in kw:
        put(params, "b", kw["bias"])


class KerasModelImport:
    """reference: KerasModelImport entry points."""

    @staticmethod
    def _open(path: str):
        import h5py

        f = h5py.File(path, "r")
        if "model_config" not in f.attrs:
            raise InvalidKerasConfigurationException(
                f"{path}: no model_config attr (not a Keras HDF5 file)")
        cfg = f.attrs["model_config"]
        if isinstance(cfg, bytes):
            cfg = cfg.decode()
        return f, json.loads(cfg)

    # ------------------------------------------------------- sequential
    @staticmethod
    def importKerasSequentialModelAndWeights(
            path: str, enforce_training_config: bool = False
    ) -> MultiLayerNetwork:
        f, cfg = KerasModelImport._open(path)
        try:
            return KerasModelImport._import_sequential(f, cfg)
        finally:
            f.close()

    @staticmethod
    def _import_sequential(f, cfg) -> MultiLayerNetwork:
        if cfg["class_name"] != "Sequential":
            raise InvalidKerasConfigurationException(
                f"model is {cfg['class_name']}, not Sequential — use "
                "importKerasModelAndWeights")
        klayers = cfg["config"]["layers"]
        input_type = None
        mapped: List[Tuple[Optional[str], Any]] = []  # (keras name, layer)
        # find last weight-bearing/mappable layer index for is_last
        last_idx = len(klayers) - 1
        for i, kl in enumerate(klayers):
            cname, lcfg = kl["class_name"], kl["config"]
            if cname == "InputLayer":
                input_type = _input_type_from_shape(lcfg["batch_shape"])
                continue
            m = _map_layer(cname, lcfg, is_last=(i == last_idx))
            if m is None:
                continue
            mapped.append((lcfg.get("name"), m))
        if input_type is None:
            raise InvalidKerasConfigurationException(
                "Sequential model without InputLayer/batch_shape")
        if not mapped:
            raise InvalidKerasConfigurationException("no layers mapped")

        lb = NeuralNetConfiguration.builder().list()
        for _, layer in mapped:
            lb.layer(layer)
        lb.setInputType(input_type)
        net = MultiLayerNetwork(lb.build())
        net.init()

        mw = f["model_weights"] if "model_weights" in f else {}
        for idx, (kname, layer) in enumerate(mapped):
            kw = _read_layer_weights(mw, kname) if kname else {}
            if kw:
                _assign_params(layer, net.params_list[idx],
                               net.states_list[idx], kw, kname)
        return net

    # ------------------------------------------------------- functional
    @staticmethod
    def importKerasModelAndWeights(
            path: str, enforce_training_config: bool = False
    ) -> ComputationGraph:
        f, cfg = KerasModelImport._open(path)
        try:
            return KerasModelImport._import_functional(f, cfg)
        finally:
            f.close()

    @staticmethod
    def _import_functional(f, cfg) -> ComputationGraph:
        if cfg["class_name"] == "Sequential":
            raise InvalidKerasConfigurationException(
                "Sequential model — use "
                "importKerasSequentialModelAndWeights")
        gc = cfg["config"]
        klayers = gc["layers"]
        out_spec = gc.get("output_layers")
        # normalize [[name,0,0],...] vs [name,0,0]
        if out_spec and not isinstance(out_spec[0], (list, tuple)):
            out_spec = [out_spec]
        output_names = [o[0] for o in out_spec]

        builder = ComputationGraphConfiguration.graphBuilder()
        input_types: List[InputType] = []
        input_names: List[str] = []
        mapped: Dict[str, Any] = {}

        for kl in klayers:
            cname, lcfg = kl["class_name"], kl["config"]
            name = lcfg["name"]
            srcs = _inbound_names(kl.get("inbound_nodes", []))
            if cname == "InputLayer":
                input_names.append(name)
                input_types.append(
                    _input_type_from_shape(lcfg["batch_shape"]))
                continue
            if cname == "Concatenate":
                builder.addVertex(name, MergeVertex(), *srcs)
                continue
            if cname in ("Add", "Subtract", "Multiply", "Average",
                         "Maximum", "Minimum"):
                op = {"Add": "Add", "Subtract": "Subtract",
                      "Multiply": "Product", "Average": "Average",
                      "Maximum": "Max", "Minimum": "Min"}[cname]
                builder.addVertex(name, ElementWiseVertex(op=op), *srcs)
                continue
            layer = _map_layer(cname, lcfg,
                               is_last=(name in output_names))
            if layer is None:
                continue
            mapped[name] = layer
            builder.addLayer(name, layer, *srcs)

        builder.addInputs(*input_names)
        builder.setInputTypes(*input_types)
        builder.setOutputs(*output_names)
        graph = ComputationGraph(builder.build())
        graph.init()

        mw = f["model_weights"] if "model_weights" in f else {}
        for name, layer in mapped.items():
            kw = _read_layer_weights(mw, name)
            if kw:
                _assign_params(layer, graph.params_map[name],
                               graph.states_map[name], kw, name)
        return graph

    # convenience dispatch (reference: importKerasModelAndWeights decides
    # by config class)
    @staticmethod
    def importModel(path: str):
        f, cfg = KerasModelImport._open(path)
        try:
            if cfg["class_name"] == "Sequential":
                return KerasModelImport._import_sequential(f, cfg)
            return KerasModelImport._import_functional(f, cfg)
        finally:
            f.close()


def _inbound_names(inbound) -> List[str]:
    """Parse Keras-3 (dict args / keras_history) and Keras-2 (nested
    list) inbound_nodes into source layer names."""
    names: List[str] = []

    def from_tensor(t):
        if isinstance(t, dict) and t.get("class_name") == "__keras_tensor__":
            names.append(t["config"]["keras_history"][0])

    for node in inbound:
        if isinstance(node, dict):  # Keras 3
            for arg in node.get("args", []):
                if isinstance(arg, list):
                    for t in arg:
                        from_tensor(t)
                else:
                    from_tensor(arg)
        elif isinstance(node, list):  # Keras 2: [[name, 0, 0, {}], ...]
            for entry in node:
                if isinstance(entry, list) and entry and \
                        isinstance(entry[0], str):
                    names.append(entry[0])
    return names
