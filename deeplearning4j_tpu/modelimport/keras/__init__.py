"""Keras HDF5 model import.

Reference: org/deeplearning4j/nn/modelimport/keras/{KerasModelImport,
KerasModel,KerasSequentialModel,KerasLayer}.java + ~60 per-layer
mappers (SURVEY.md §2.32). The reference reads HDF5 via JavaCPP; here
h5py reads the same format, and the canonical NHWC layout means Keras
weight tensors (HWIO convs, (in,out) dense kernels, IFCO LSTM gates)
map to our parameter layouts with NO transposition — the reference
needs NCHW permutes, we don't.
"""

from deeplearning4j_tpu.modelimport.keras.keras_import import (
    KerasModelImport, registerCustomLayer, unregisterCustomLayer,
)

__all__ = ["KerasModelImport", "registerCustomLayer",
           "unregisterCustomLayer"]
