"""Model import (reference: deeplearning4j-modelimport — SURVEY.md
§2.32 Keras HDF5 import, §2.14 TF frozen-graph + ONNX import)."""
