"""ONNX ModelProto → SameDiff.

Reference: nd4j/samediff-import/samediff-import-onnx — OnnxFrameworkImporter
walking the ONNX graph through an OpMappingRegistry into SameDiff ops
(SURVEY.md §2.14). Same architecture as our TF importer: per-op mappers
emit into a SameDiff graph that whole-graph-compiles under XLA.

Layout: ONNX is NCHW/OIHW. The importer keeps tensors in ONNX's NCHW
layout end-to-end (so graph outputs match ONNX semantics exactly) and
brackets each conv/pool with NCHW<->NHWC transposes into our NHWC TPU
kernels — XLA's layout assignment cancels adjacent transposes between
chained convs, so the compiled program stays in NHWC on the hot path.

Initializers import as CONSTANTs; use
`SameDiff.convertConstantsToVariables` to fine-tune an imported model
(same contract as the reference).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable
from deeplearning4j_tpu.modelimport.onnx.onnx_proto import (
    GraphProto, ModelProto, NodeProto, decode_model,
)


class OnnxImportError(ValueError):
    pass


# ONNX-semantics helper ops live with the op set (ops/onnx_compat.py)
# so a bare `import deeplearning4j_tpu.ops` registers the full registry
from deeplearning4j_tpu.ops import onnx_compat  # noqa: E402,F401
from deeplearning4j_tpu.modelimport import trace as mapper_trace  # noqa: E402


# Default-attribute semantics changed across opsets (Hardmax/Softmax
# axis, reduce axes, ...). importGraph stamps the model's ai.onnx opset
# here for the duration of the walk (sub-graph walks run inside the
# top-level walk, so one slot suffices); 13 = modern default when a
# mapper is driven outside importGraph (unit micro-graphs).
_ACTIVE_OPSET = 13


class _Ctx:
    def __init__(self, sd: SameDiff, node: NodeProto,
                 inputs: List[Optional[SDVariable]],
                 static: List[Optional[np.ndarray]], avals=None):
        self.sd = sd
        self.node = node
        self.inputs = inputs
        self._static = static
        self.avals = avals  # var name -> jax.ShapeDtypeStruct

    @property
    def opset(self) -> int:
        return _ACTIVE_OPSET

    def attr(self, name: str, default=None):
        return self.node.attributes.get(name, default)

    def static_np(self, i: int) -> np.ndarray:
        v = self._static[i] if i < len(self._static) else None
        if v is None:
            raise OnnxImportError(
                f"node {self.node.name or self.node.op_type}: input {i} "
                "must be a constant/initializer (XLA static-shape "
                "discipline)")
        return v

    def maybe_static(self, i: int) -> Optional[np.ndarray]:
        return self._static[i] if i < len(self._static) else None

    def op(self, op_name: str, inputs: Sequence[SDVariable], n_out: int = 1,
           **attrs):
        return self.sd._op(op_name, [v.name for v in inputs], n_out=n_out,
                           **attrs)

    # NCHW <-> NHWC brackets for the conv/pool kernels
    def to_nhwc(self, v: SDVariable) -> SDVariable:
        return self.op("transpose", [v], permute=[0, 2, 3, 1])

    def to_nchw(self, v: SDVariable) -> SDVariable:
        return self.op("transpose", [v], permute=[0, 3, 1, 2])


class OnnxOpMappingRegistry:
    _mappers: Dict[str, Callable[[_Ctx], Any]] = {}

    @classmethod
    def register(cls, *op_types: str):
        def deco(fn):
            for name in op_types:
                cls._mappers[name] = fn
            return fn
        return deco

    @classmethod
    def get(cls, op_type: str):
        try:
            fn = cls._mappers[op_type]
        except KeyError:
            raise OnnxImportError(
                f"no mapper for ONNX op {op_type!r} (have "
                f"{len(cls._mappers)}; add one via "
                "OnnxOpMappingRegistry.register)") from None
        mapper_trace.record("onnx", op_type)
        return fn

    @classmethod
    def coverage(cls) -> List[str]:
        return sorted(cls._mappers)


R = OnnxOpMappingRegistry.register


# ----------------------------------------------------------- elementwise
_UNARY = {
    "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh", "Exp": "exp",
    "Log": "log", "Sqrt": "sqrt", "Abs": "abs", "Erf": "erf",
    "Floor": "floor", "Ceil": "ceil", "Round": "round", "Sign": "sign",
    "Softplus": "softplus", "Softsign": "softsign", "Sin": "sin",
    "Cos": "cos", "Tan": "tan", "Asin": "asin", "Acos": "acos",
    "Atan": "atan", "Sinh": "sinh", "Cosh": "cosh", "Mish": "mish",
    "Reciprocal": "reciprocal", "IsNaN": "isnan", "IsInf": "isinf",
}
for _onnx_name, _our in _UNARY.items():
    @R(_onnx_name)
    def _unary(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:1])

_BINARY = {"Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div",
           "Pow": "pow_pairwise"}
for _onnx_name, _our in _BINARY.items():
    @R(_onnx_name)
    def _binary(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:2])

@R("Mod")
def _mod(ctx):
    # fmod=0 (default): python/floor semantics, sign follows divisor;
    # fmod=1: C fmod, sign follows dividend (the attr was previously
    # ignored — caught by the mapper battery)
    our = "fmod" if int(ctx.attr("fmod", 0)) else "mod"
    return ctx.op(our, ctx.inputs[:2])


# Min/Max are VARIADIC in ONNX (1..N inputs, numpy-broadcast fold) —
# truncating to 2 silently dropped inputs 3+ (caught by the mapper
# battery, tests/test_onnx_mapper_battery.py)
for _onnx_name, _our in (("Min", "min_pairwise"), ("Max", "max_pairwise")):
    @R(_onnx_name)
    def _minmax_n(ctx, _o=_our):
        out = ctx.inputs[0]
        for v in ctx.inputs[1:]:
            out = ctx.op(_o, [out, v])
        return out


@R("Neg")
def _neg(ctx):
    return ctx.op("rsub", ctx.inputs[:1] + [ctx.sd.constant_like(0.0)])


@R("Sum")
def _sum_n(ctx):
    out = ctx.inputs[0]
    for v in ctx.inputs[1:]:
        out = ctx.op("add", [out, v])
    return out


@R("LeakyRelu")
def _leaky(ctx):
    return ctx.op("leakyrelu", ctx.inputs[:1],
                  alpha=float(ctx.attr("alpha", 0.01)))


@R("Elu")
def _elu(ctx):
    return ctx.op("elu", ctx.inputs[:1], alpha=float(ctx.attr("alpha", 1.0)))


@R("Selu")
def _selu(ctx):
    return ctx.op("selu", ctx.inputs[:1])


@R("HardSigmoid")
def _hardsigmoid(ctx):
    # alpha/beta attrs (defaults 0.2/0.5) — the fixed-constant
    # `hardsigmoid` op only covers the default pair (caught by the
    # mapper battery)
    alpha = float(ctx.attr("alpha", 0.2))
    beta = float(ctx.attr("beta", 0.5))
    if (alpha, beta) == (0.2, 0.5):
        return ctx.op("hardsigmoid", ctx.inputs[:1])
    a = ctx.sd.constant(f"{ctx.node.output[0]}_hsa", np.float32(alpha))
    b = ctx.sd.constant(f"{ctx.node.output[0]}_hsb", np.float32(beta))
    ax = ctx.op("mul", [ctx.inputs[0], a])
    axb = ctx.op("add", [ax, b])
    return ctx.op("clip_by_value", [axb], lo=0.0, hi=1.0)


@R("Gelu")
def _gelu(ctx):
    return ctx.op("gelu", ctx.inputs[:1])


@R("ThresholdedRelu")
def _thresholded(ctx):
    return ctx.op("thresholdedrelu", ctx.inputs[:1],
                  theta=float(ctx.attr("alpha", 1.0)))


@R("Clip")
def _clip(ctx):
    lo = ctx.attr("min")
    hi = ctx.attr("max")
    if lo is None and len(ctx.inputs) > 1 and ctx.inputs[1] is not None:
        lo = float(ctx.static_np(1))
    if hi is None and len(ctx.inputs) > 2 and ctx.inputs[2] is not None:
        hi = float(ctx.static_np(2))
    return ctx.op("clip_by_value", ctx.inputs[:1],
                  lo=float(lo if lo is not None else -np.inf),
                  hi=float(hi if hi is not None else np.inf))


def _opset13_axis_family(ctx, opname):
    """Softmax/LogSoftmax/Hardmax share the opset-13 semantics change:
    >=13 is per-axis (default -1); <13 is default axis=1 with
    COERCE-TO-2D — the op runs over the flattened trailing dims
    [prod(:axis), prod(axis:)], materially different when >1 trailing
    dim (onnx Operators.md changelog)."""
    if ctx.opset >= 13:
        return ctx.op(opname, ctx.inputs[:1],
                      axis=int(ctx.attr("axis", -1)))
    axis = int(ctx.attr("axis", 1))
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is None:
        if axis == -1:
            # coerce-to-2D over [prod(:-1), last] IS per-last-axis —
            # no shape needed for this one case
            return ctx.op(opname, ctx.inputs[:1], axis=-1)
        raise OnnxImportError(
            f"{ctx.node.name}: {ctx.node.op_type} at opset "
            f"{ctx.opset} < 13 uses coerce-to-2D semantics and needs "
            "a known input shape")
    shape = tuple(int(d) for d in aval.shape)
    if axis < 0:
        axis += len(shape)
    if axis == len(shape) - 1 or all(d == 1 for d in shape[axis:-1]):
        return ctx.op(opname, ctx.inputs[:1], axis=-1)
    rows = int(np.prod(shape[:axis], dtype=np.int64))
    cols = int(np.prod(shape[axis:], dtype=np.int64))
    flat = ctx.op("reshape", ctx.inputs[:1], shape=[rows, cols])
    out = ctx.op(opname, [flat], axis=-1)
    return ctx.op("reshape", [out], shape=list(shape))


@R("Softmax")
def _softmax(ctx):
    return _opset13_axis_family(ctx, "softmax")


@R("LogSoftmax")
def _log_softmax(ctx):
    return _opset13_axis_family(ctx, "log_softmax")


@R("PRelu")
def _prelu(ctx):
    return ctx.op("prelu", ctx.inputs[:2])


# ------------------------------------------------------------- matmul/fc
@R("MatMul")
def _matmul(ctx):
    return ctx.op("matmul", ctx.inputs[:2])


@R("Gemm")
def _gemm(ctx):
    a, b = ctx.inputs[0], ctx.inputs[1]
    alpha = float(ctx.attr("alpha", 1.0))
    beta = float(ctx.attr("beta", 1.0))
    out = ctx.op("matmul", [a, b],
                 transpose_a=bool(ctx.attr("transA", 0)),
                 transpose_b=bool(ctx.attr("transB", 0)))
    if alpha != 1.0:
        out = ctx.op("mul", [out, ctx.sd.constant_like(alpha)])
    if len(ctx.inputs) > 2 and ctx.inputs[2] is not None:
        c = ctx.inputs[2]
        if beta != 1.0:
            c = ctx.op("mul", [c, ctx.sd.constant_like(beta)])
        out = ctx.op("add", [out, c])
    return out


# ----------------------------------------------------------------- shape
@R("Identity")
def _identity(ctx):
    return ctx.op("add", [ctx.inputs[0], ctx.sd.constant_like(0.0)])


@R("Dropout")
def _dropout(ctx):
    # inference import: dropout is identity (reference does the same)
    return ctx.op("add", [ctx.inputs[0], ctx.sd.constant_like(0.0)])


@R("Reshape")
def _reshape(ctx):
    shape = [int(s) for s in ctx.static_np(1)]
    return ctx.op("onnx_reshape", ctx.inputs[:1], shape=shape)


@R("Transpose")
def _transpose(ctx):
    perm = ctx.attr("perm")
    if perm is None:
        raise OnnxImportError("Transpose without perm unsupported")
    return ctx.op("transpose", ctx.inputs[:1],
                  permute=[int(p) for p in perm])


@R("Flatten")
def _flatten(ctx):
    return ctx.op("onnx_flatten", ctx.inputs[:1],
                  axis=int(ctx.attr("axis", 1)))


@R("Concat")
def _concat(ctx):
    return ctx.op("concat", ctx.inputs, axis=int(ctx.attr("axis", 0)))


def _axes_attr_or_input(ctx, input_idx=1):
    """ONNX moved reduce/squeeze axes from an attribute (opset <13/18)
    to an optional tensor input; accept both, None when absent."""
    axes = ctx.attr("axes")
    if axes is None and len(ctx.inputs) > input_idx \
            and ctx.inputs[input_idx] is not None:
        axes = ctx.static_np(input_idx)
    # empty axes == absent axes == reduce over all (pre-opset-18 rule)
    return [int(a) for a in axes] if axes is not None and len(axes) else None


def _reduce_kwargs(ctx):
    return dict(dimensions=_axes_attr_or_input(ctx),
                keep_dims=bool(ctx.attr("keepdims", 1)))


@R("Squeeze")
def _squeeze(ctx):
    axes = _axes_attr_or_input(ctx)
    return ctx.op("squeeze", ctx.inputs[:1],
                  axis=tuple(axes) if axes else None)


@R("Unsqueeze")
def _unsqueeze(ctx):
    axes = ctx.attr("axes")
    if axes is None and len(ctx.inputs) > 1:
        axes = [int(a) for a in ctx.static_np(1)]
    out = ctx.inputs[0]
    for a in sorted(int(x) for x in axes):
        out = ctx.op("expand_dims", [out], axis=a)
    return out


@R("Gather")
def _gather(ctx):
    idx = ctx.maybe_static(1)
    if idx is not None:
        indices = ctx.sd.constant(
            f"{ctx.node.output[0]}_idx", idx.astype(np.int32))
    else:
        indices = ctx.inputs[1]
    return ctx.op("gather", [ctx.inputs[0], indices],
                  axis=int(ctx.attr("axis", 0)))


@R("Slice")
def _slice(ctx):
    if ctx.attr("starts") is not None:  # opset < 10: attrs
        starts = [int(v) for v in ctx.attr("starts")]
        ends = [int(v) for v in ctx.attr("ends")]
        axes = [int(v) for v in ctx.attr("axes",
                                         list(range(len(starts))))]
        steps = [1] * len(starts)
    else:
        starts = [int(v) for v in ctx.static_np(1)]
        ends = [int(v) for v in ctx.static_np(2)]
        axes = ([int(v) for v in ctx.static_np(3)]
                if len(ctx.inputs) > 3 and ctx.maybe_static(3) is not None
                else list(range(len(starts))))
        steps = ([int(v) for v in ctx.static_np(4)]
                 if len(ctx.inputs) > 4 and ctx.maybe_static(4) is not None
                 else [1] * len(starts))
    return ctx.op("onnx_slice", ctx.inputs[:1], starts=starts, ends=ends,
                  axes=axes, steps=steps)


@R("Tile")
def _tile(ctx):
    reps = [int(v) for v in ctx.static_np(1)]
    return ctx.op("tile", ctx.inputs[:1], reps=reps)


@R("Expand")
def _expand(ctx):
    # ONNX Expand is BIDIRECTIONAL numpy broadcasting: the requested
    # shape's 1-dims adopt the input's size (Expand([1,1,64],[2,1,1])
    # -> [2,1,64]) — plain broadcast_to rejects that form
    shape = [int(v) for v in ctx.static_np(1)]
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is not None:
        ins = list(aval.shape)
        n = max(len(ins), len(shape))
        ins = [1] * (n - len(ins)) + ins
        req = [1] * (n - len(shape)) + shape
        shape = [max(a, b) for a, b in zip(ins, req)]
    return ctx.op("broadcast_to", ctx.inputs[:1], shape=shape)


@R("Pad")
def _pad(ctx):
    pads = ctx.attr("pads")
    if pads is None:
        pads = [int(v) for v in ctx.static_np(1)]
    mode = ctx.attr("mode", "constant")
    if mode != "constant":
        raise OnnxImportError(f"Pad mode {mode!r} unsupported")
    n = len(pads) // 2
    pairs = [[int(pads[i]), int(pads[i + n])] for i in range(n)]
    return ctx.op("pad", ctx.inputs[:1], paddings=pairs)


@R("Cast")
def _cast(ctx):
    to = int(ctx.attr("to", 1))
    from deeplearning4j_tpu.modelimport.onnx.onnx_proto import TensorProto
    np_dt = TensorProto._DTYPES.get(to, np.float32)
    return ctx.op("cast", ctx.inputs[:1], dtype=np.dtype(np_dt).name)


@R("Shape")
def _shape(ctx):
    """Static shapes fold to an import-time constant (real exporters
    emit Shape->Gather->Concat reshape subgraphs around attention; the
    whole chain folds via the importer's int-subgraph folding)."""
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is None or any(d is None or d < 0 for d in aval.shape):
        raise OnnxImportError(
            f"{ctx.node.name or ctx.node.op_type}: Shape of a tensor "
            "with unknown dims — re-export with static shapes (XLA "
            "compiles static programs)")
    # opset >= 15: optional start/end attrs slice the shape vector;
    # spec rule: negative values add rank, then CLAMP to [0, rank]
    rank = len(aval.shape)

    def _clamp(v):
        v = int(v)
        if v < 0:
            v += rank
        return max(0, min(v, rank))

    start = _clamp(ctx.attr("start", 0))
    end = rank if ctx.attr("end") is None else _clamp(ctx.attr("end"))
    return ctx.sd.constant(ctx.node.output[0],
                           np.asarray(aval.shape[start:end], np.int64))


@R("Constant")
def _constant(ctx):
    val = ctx.attr("value")
    if val is None:
        val = np.asarray(ctx.attr("value_float", 0.0), np.float32)
    return ctx.sd.constant(ctx.node.output[0], np.asarray(val))


@R("ConstantOfShape")
def _constant_of_shape(ctx):
    # output dtype = the value tensor's dtype (spec; default f32 zero)
    # — torch's expand-shape idiom fills int64 ones and feeds the
    # result into shape arithmetic, so forcing f32 breaks const folding
    shape = [int(v) for v in ctx.static_np(0)]
    val = ctx.attr("value")
    if val is not None:
        v = np.asarray(val)
        arr = np.full(shape, v.ravel()[0], v.dtype)
    else:
        arr = np.zeros(shape, np.float32)
    return ctx.sd.constant(ctx.node.output[0], arr)


@R("Where")
def _where(ctx):
    return ctx.op("where", ctx.inputs[:3])


for _onnx_name, _our in {"Equal": "eq", "Greater": "gt", "Less": "lt",
                         "GreaterOrEqual": "gte",
                         "LessOrEqual": "lte"}.items():
    @R(_onnx_name)
    def _cmp(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:2])


# ---------------------------------------------------------- reductions
_REDUCE = {"ReduceSum": "reduce_sum", "ReduceMean": "reduce_mean",
           "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
           "ReduceProd": "reduce_prod"}
for _onnx_name, _our in _REDUCE.items():
    @R(_onnx_name)
    def _reduce(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:1], **_reduce_kwargs(ctx))


@R("ArgMax")
def _argmax(ctx):
    out = ctx.op("argmax", ctx.inputs[:1],
                 dimensions=int(ctx.attr("axis", 0)))
    if int(ctx.attr("keepdims", 1)):
        out = ctx.op("expand_dims", [out], axis=int(ctx.attr("axis", 0)))
    return out


# -------------------------------------------------------------- conv/pool
def _conv_padding_args(ctx, default_kernel=None):
    auto = ctx.attr("auto_pad", "NOTSET")
    pads = ctx.attr("pads")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME", None
    if pads is None or not any(pads):
        return "VALID", None
    n = len(pads) // 2
    # [x1b, x2b, x1e, x2e] -> [(b,e), ...] per spatial dim
    return None, [(int(pads[i]), int(pads[i + n])) for i in range(n)]


def _explicit_pad_nhwc(ctx, v, spatial_pads):
    pairs = [[0, 0]] + [list(p) for p in spatial_pads] + [[0, 0]]
    return ctx.op("pad", [v], paddings=pairs)


@R("Conv")
def _conv(ctx):
    x = ctx.to_nhwc(ctx.inputs[0])
    w = ctx.inputs[1]                         # OIHW
    w = ctx.op("transpose", [w], permute=[2, 3, 1, 0])  # -> HWIO
    strides = [int(s) for s in ctx.attr("strides", [1, 1])]
    dil = [int(d) for d in ctx.attr("dilations", [1, 1])]
    group = int(ctx.attr("group", 1))
    pad_mode, spatial = _conv_padding_args(ctx)
    if spatial is not None:
        if len(spatial) == 2:
            # conv2d takes ((lo,hi),(lo,hi)) directly — no separate pad
            # node to rely on XLA re-fusing (pool padding semantics
            # differ, so _pool keeps the explicit pad op)
            pad_mode = tuple(tuple(p) for p in spatial)
        else:
            x = _explicit_pad_nhwc(ctx, x, spatial)
            pad_mode = "VALID"
    # ONNX OIHW weights transpose to (kH, kW, I/g, O) above — exactly
    # the grouped-HWIO layout conv2d's feature_group_count expects
    out = ctx.op("conv2d", [x, w], strides=strides, padding=pad_mode,
                 dilation=dil, groups=group)
    if len(ctx.inputs) > 2 and ctx.inputs[2] is not None:
        out = ctx.op("add", [out, ctx.inputs[2]])
    return ctx.to_nchw(out)


@R("MaxPool", "AveragePool")
def _pool(ctx):
    x = ctx.to_nhwc(ctx.inputs[0])
    kernel = [int(k) for k in ctx.attr("kernel_shape")]
    strides = [int(s) for s in ctx.attr("strides", kernel)]
    pad_mode, spatial = _conv_padding_args(ctx)
    if spatial is not None:
        x = _explicit_pad_nhwc(ctx, x, spatial)
        pad_mode = "VALID"
    op = "maxpool2d" if ctx.node.op_type == "MaxPool" else "avgpool2d"
    out = ctx.op(op, [x], kernel=kernel, strides=strides, padding=pad_mode)
    return ctx.to_nchw(out)


@R("GlobalAveragePool")
def _gap(ctx):
    out = ctx.op("reduce_mean", ctx.inputs[:1], dimensions=[2, 3],
                 keep_dims=True)
    return out


@R("GlobalMaxPool")
def _gmp(ctx):
    return ctx.op("reduce_max", ctx.inputs[:1], dimensions=[2, 3],
                  keep_dims=True)


@R("BatchNormalization")
def _bn(ctx):
    x, scale, bias, mean, var = ctx.inputs[:5]
    eps = float(ctx.attr("epsilon", 1e-5))
    # params are [C]; x is NCHW -> reshape params to [C,1,1] to broadcast
    def chan(v):
        return ctx.op("reshape", [v], shape=[-1, 1, 1])
    xm = ctx.op("sub", [x, chan(mean)])
    inv = ctx.op("rsqrt", [ctx.op(
        "add", [chan(var), ctx.sd.constant_like(eps)])])
    return ctx.op("add", [ctx.op("mul", [ctx.op("mul", [xm, inv]),
                                         chan(scale)]), chan(bias)])


@R("LRN")
def _lrn(ctx):
    x = ctx.to_nhwc(ctx.inputs[0])
    size = int(ctx.attr("size", 5))
    out = ctx.op("lrn", [x], depth_radius=size // 2,
                 bias=float(ctx.attr("bias", 1.0)),
                 alpha=float(ctx.attr("alpha", 1e-4)) / size,
                 beta=float(ctx.attr("beta", 0.75)))
    return ctx.to_nchw(out)


# ------------------------------------------------------- recurrent ops
# (ONNX LSTM/GRU/RNN — what torch.onnx.export emits for nn.LSTM/GRU/RNN
# and what keras/sklearn exporters emit with the reset-before GRU form;
# reference: samediff-import-onnx maps these onto nd4j's flexible
# lstmLayer, incl. cell clip / coupled gates / activations / ragged
# sequence lengths — SURVEY.md §2.14)
def _rnn_setup(ctx, n_gates, hidden):
    """Common decode: batch-major x, per-direction packed weights.
    ONNX tensor layout (layout=0): X [T,N,in]; W [dirs, gates*H, in];
    R [dirs, gates*H, H]; B [dirs, 2*gates*H] (Wb ++ Rb); layout=1
    swaps X to [N,T,in] and states/Y to batch-major. Weights must be
    constants (true for every real exporter; re-packed at import).

    Returns (x [N,T,in], W, R, B, dirs, layout, seq_lens_var, clip)."""
    clip = float(ctx.attr("clip", 0.0) or 0.0)
    layout = int(ctx.attr("layout", 0))
    W = ctx.static_np(1)
    R = ctx.static_np(2)
    dirs = W.shape[0]
    if len(ctx.inputs) > 3 and ctx.inputs[3] is not None:
        B = ctx.static_np(3)   # present-but-runtime bias must be LOUD,
        # not silently zeroed; static_np raises for non-constants
    else:
        B = np.zeros((dirs, 2 * n_gates * hidden), np.float32)
    seq_lens = None
    if len(ctx.inputs) > 4 and ctx.inputs[4] is not None:
        seq_lens = ctx.inputs[4]
        sl = ctx.maybe_static(4)
        p = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
        t_axis = 1 if layout else 0
        t = int(p.shape[t_axis]) if p is not None and p.shape else None
        if sl is not None and t is not None and sl.size \
                and np.all(sl == t):
            seq_lens = None  # full-length: skip the masking machinery
    x = ctx.inputs[0] if layout else \
        ctx.op("transpose", ctx.inputs[:1], permute=[1, 0, 2])
    return x, W, R, B, dirs, layout, seq_lens, clip


def _rnn_state(ctx, input_idx, d, layout=0):
    """initial_h/initial_c -> direction d's [N, H] (the state tensor is
    [dirs, N, H] for layout=0, [N, dirs, H] for layout=1)."""
    if len(ctx.inputs) <= input_idx or ctx.inputs[input_idx] is None:
        return None
    idx = ctx.sd.constant(f"{ctx.node.output[0]}_d{input_idx}_{d}",
                          np.int32(d))
    return ctx.op("gather", [ctx.inputs[input_idx], idx],
                  axis=1 if layout else 0)


def _rnn_acts(ctx, per_dir, dirs, defaults):
    """Parse activations/activation_alpha/activation_beta into per-
    direction lists of (name, alpha, beta) triples, or None when the
    attrs just restate the defaults (keeps the graph attr small)."""
    names = ctx.attr("activations")
    if not names:
        return None
    names = [n.decode() if isinstance(n, bytes) else str(n)
             for n in names]
    if [n.lower() for n in names] == [d.lower()
                                      for d in defaults] * dirs:
        return None
    alphas = list(ctx.attr("activation_alpha") or [])
    betas = list(ctx.attr("activation_beta") or [])
    if len(names) != per_dir * dirs:
        raise OnnxImportError(
            f"{ctx.node.name}: {len(names)} activations for "
            f"{dirs} direction(s) x {per_dir}")
    specs = [(names[i],
              float(alphas[i]) if i < len(alphas) else None,
              float(betas[i]) if i < len(betas) else None)
             for i in range(len(names))]
    return [specs[d * per_dir:(d + 1) * per_dir] for d in range(dirs)]


def _rnn_outputs(ctx, ys_list, states, layout=0):
    """Per-direction [N,T,H] outputs -> ONNX Y (+ final states).
    layout=0: Y [T, dirs, N, H], states [dirs, N, H];
    layout=1: Y [N, T, dirs, H], states [N, dirs, H]."""
    if layout:
        y = ctx.op("stack", ys_list, axis=2)
        outs = [y]
        for group in states:
            outs.append(ctx.op("stack", group, axis=1))
        return tuple(outs)
    ys_t = [ctx.op("transpose", [y], permute=[1, 0, 2])
            for y in ys_list]
    y = ctx.op("stack", ys_t, axis=1)
    outs = [y]
    for group in states:
        outs.append(ctx.op("stack", group, axis=0))
    return tuple(outs)


@R("LSTM")
def _onnx_lstm(ctx):
    hs = int(ctx.attr("hidden_size"))
    direction = ctx.attr("direction", "forward")
    x, W, R, B, dirs, layout, seq_lens, clip = _rnn_setup(ctx, 4, hs)
    acts_by_dir = _rnn_acts(ctx, 3, dirs, ["Sigmoid", "Tanh", "Tanh"])
    input_forget = bool(int(ctx.attr("input_forget", 0)))
    P = None
    if len(ctx.inputs) > 7 and ctx.inputs[7] is not None:
        P = ctx.static_np(7)   # peepholes [dirs, 3H] as (p_i, p_o, p_f)
    order = [0, 2, 3, 1]          # ONNX iofc -> our i,f,g(=c),o
    ys_list, h_list, c_list = [], [], []
    for d in range(dirs):
        w_ih = W[d].reshape(4, hs, -1)[order].reshape(4 * hs, -1).T
        w_hh = R[d].reshape(4, hs, hs)[order].reshape(4 * hs, hs).T
        b = (B[d][:4 * hs] + B[d][4 * hs:]) \
            .reshape(4, hs)[order].reshape(-1)
        base = f"{ctx.node.output[0]}_d{d}"
        ins = [x,
               ctx.sd.constant(base + "_wih", w_ih.astype(np.float32)),
               ctx.sd.constant(base + "_whh", w_hh.astype(np.float32)),
               ctx.sd.constant(base + "_b", b.astype(np.float32))]
        h0 = _rnn_state(ctx, 5, d, layout)
        c0 = _rnn_state(ctx, 6, d, layout)
        has_state = h0 is not None or c0 is not None
        if has_state:
            # ONNX allows either state alone (other defaults to zeros)
            if h0 is None:
                h0 = ctx.op("zeros_like", [c0])
            if c0 is None:
                c0 = ctx.op("zeros_like", [h0])
            ins += [h0, c0]
        if seq_lens is not None:
            ins.append(seq_lens)
        if P is not None:
            pi, po, pf = (P[d][:hs], P[d][hs:2 * hs], P[d][2 * hs:])
            ins.append(ctx.sd.constant(
                base + "_peep",
                np.stack([pi, pf, po]).astype(np.float32)))
        reverse = (direction == "reverse") or d == 1
        ys, hT, cT = ctx.op(
            "onnx_lstm_seq", ins, n_out=3, reverse=reverse,
            has_state=has_state, has_lens=seq_lens is not None,
            has_peep=P is not None, cell_clip=clip,
            input_forget=input_forget,
            acts=acts_by_dir[d] if acts_by_dir else None)
        ys_list.append(ys)
        h_list.append(hT)
        c_list.append(cT)
    return _rnn_outputs(ctx, ys_list, [h_list, c_list], layout)


@R("GRU")
def _onnx_gru(ctx):
    hs = int(ctx.attr("hidden_size"))
    direction = ctx.attr("direction", "forward")
    x, W, R, B, dirs, layout, seq_lens, clip = _rnn_setup(ctx, 3, hs)
    acts_by_dir = _rnn_acts(ctx, 2, dirs, ["Sigmoid", "Tanh"])
    lbr = bool(int(ctx.attr("linear_before_reset", 0)))
    order = [1, 0, 2]             # ONNX z,r,h -> our r,z,n
    ys_list, h_list = [], []
    for d in range(dirs):
        w_ih = W[d].reshape(3, hs, -1)[order].reshape(3 * hs, -1).T
        w_hh = R[d].reshape(3, hs, hs)[order].reshape(3 * hs, hs).T
        wb = B[d][:3 * hs].reshape(3, hs)[order].reshape(-1)
        rb = B[d][3 * hs:].reshape(3, hs)[order].reshape(-1)
        base = f"{ctx.node.output[0]}_d{d}"
        ins = [x,
               ctx.sd.constant(base + "_wih", w_ih.astype(np.float32)),
               ctx.sd.constant(base + "_whh", w_hh.astype(np.float32)),
               ctx.sd.constant(base + "_b", wb.astype(np.float32)),
               ctx.sd.constant(base + "_rb", rb.astype(np.float32))]
        h0 = _rnn_state(ctx, 5, d, layout)
        if h0 is not None:
            ins.append(h0)
        if seq_lens is not None:
            ins.append(seq_lens)
        reverse = (direction == "reverse") or d == 1
        ys, hT = ctx.op(
            "onnx_gru_seq", ins, n_out=2, reverse=reverse,
            has_state=h0 is not None, has_lens=seq_lens is not None,
            linear_before_reset=lbr, cell_clip=clip,
            acts=acts_by_dir[d] if acts_by_dir else None)
        ys_list.append(ys)
        h_list.append(hT)
    return _rnn_outputs(ctx, ys_list, [h_list], layout)


@R("RNN")
def _onnx_rnn(ctx):
    hs = int(ctx.attr("hidden_size"))
    direction = ctx.attr("direction", "forward")
    x, W, R, B, dirs, layout, seq_lens, clip = _rnn_setup(ctx, 1, hs)
    acts_by_dir = _rnn_acts(ctx, 1, dirs, ["Tanh"])
    ys_list, h_list = [], []
    for d in range(dirs):
        w_ih = W[d].T
        w_hh = R[d].T
        b = B[d][:hs] + B[d][hs:]
        base = f"{ctx.node.output[0]}_d{d}"
        ins = [x,
               ctx.sd.constant(base + "_wih", w_ih.astype(np.float32)),
               ctx.sd.constant(base + "_whh", w_hh.astype(np.float32)),
               ctx.sd.constant(base + "_b", b.astype(np.float32))]
        h0 = _rnn_state(ctx, 5, d, layout)
        if h0 is not None:
            ins.append(h0)
        if seq_lens is not None:
            ins.append(seq_lens)
        rev = (direction == "reverse") or d == 1
        ys, hT = ctx.op(
            "onnx_rnn_seq", ins, n_out=2, reverse=rev,
            has_state=h0 is not None, has_lens=seq_lens is not None,
            cell_clip=clip,
            acts=acts_by_dir[d] if acts_by_dir else None)
        ys_list.append(ys)
        h_list.append(hT)
    return _rnn_outputs(ctx, ys_list, [h_list], layout)


@R("LayerNormalization")
def _layer_norm(ctx):
    x, scale = ctx.inputs[0], ctx.inputs[1]
    bias = ctx.inputs[2] if len(ctx.inputs) > 2 else None
    eps = float(ctx.attr("epsilon", 1e-5))
    ins = [x, scale] + ([bias] if bias is not None else [])
    return ctx.op("layer_norm", ins, eps=eps)


# --------------------------------------------------- breadth (round 4)
@R("ArgMin")
def _argmin(ctx):
    return ctx.op("argmin", ctx.inputs[:1],
                  dimensions=int(ctx.attr("axis", 0)),
                  keep_dims=bool(ctx.attr("keepdims", 1)))


for _onnx_name, _our in {"And": "logical_and", "Or": "logical_or",
                         "Xor": "logical_xor"}.items():
    @R(_onnx_name)
    def _logic2(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:2])


@R("Not")
def _logic_not(ctx):
    return ctx.op("logical_not", ctx.inputs[:1])


@R("Split")
def _split(ctx):
    axis = int(ctx.attr("axis", 0))
    n_out = len(ctx.node.output)
    sizes = ctx.attr("split")                       # opset < 13: attr
    if sizes is None and len(ctx.inputs) > 1 and ctx.inputs[1] is not None:
        sizes = [int(v) for v in ctx.static_np(1)]  # opset >= 13: input
    if sizes is None:
        return ctx.op("split", ctx.inputs[:1], n_out=n_out,
                      num_splits=n_out, axis=axis)
    sizes = [int(s) for s in sizes]
    if len(set(sizes)) == 1:
        return ctx.op("split", ctx.inputs[:1], n_out=n_out,
                      num_splits=n_out, axis=axis)
    return ctx.op("split_v", ctx.inputs[:1], n_out=n_out, sizes=sizes,
                  axis=axis)


@R("ConvTranspose")
def _conv_transpose(ctx):
    """Maps onto deconv2d (out = s*(in-1) + k - 2p): symmetric pads,
    no output_padding — the torch ConvTranspose2d export defaults."""
    if int(ctx.attr("group", 1)) != 1:
        raise OnnxImportError(
            f"{ctx.node.name}: grouped ConvTranspose not supported")
    if any(int(v) for v in ctx.attr("output_padding", []) or []):
        raise OnnxImportError(
            f"{ctx.node.name}: output_padding not supported")
    if any(int(d) != 1 for d in ctx.attr("dilations", []) or []):
        raise OnnxImportError(
            f"{ctx.node.name}: dilated ConvTranspose not supported")
    strides = [int(s) for s in ctx.attr("strides", [1, 1])]
    auto = ctx.attr("auto_pad", "NOTSET")
    if auto == "SAME_LOWER":
        raise OnnxImportError(
            f"{ctx.node.name}: ConvTranspose SAME_LOWER not supported "
            "(odd pad lands on the opposite side)")
    if auto == "SAME_UPPER":
        padding = "SAME"
    else:
        pads = [int(p) for p in ctx.attr("pads", [0, 0, 0, 0])]
        n = len(pads) // 2
        if pads[:n] != pads[n:]:
            raise OnnxImportError(
                f"{ctx.node.name}: asymmetric ConvTranspose pads not "
                "supported")
        padding = tuple(pads[:n]) if any(pads) else "VALID"
    x = ctx.to_nhwc(ctx.inputs[0])
    # ONNX W is (Cin, Cout, kH, kW) -> deconv2d wants (kH, kW, Cin, Cout);
    # ONNX/torch ConvTranspose is the GRADIENT of a forward conv, i.e.
    # correlation with the spatially FLIPPED kernel — lax.conv_transpose
    # (deconv2d) zero-inserts then correlates unflipped, so flip here
    w = ctx.op("transpose", [ctx.inputs[1]], permute=[2, 3, 0, 1])
    w = ctx.op("reverse", [w], dimensions=[0, 1])
    ins = [x, w] + ([ctx.inputs[2]] if len(ctx.inputs) > 2
                    and ctx.inputs[2] is not None else [])
    out = ctx.op("deconv2d", ins, strides=strides, padding=padding)
    return ctx.to_nchw(out)


def _resize_src_coords(out_size, in_size, coord, scale=None):
    """ONNX output-index -> continuous input coordinate per
    coordinate_transformation_mode (spec table, opset 11+).

    When the model provides a SCALE (not sizes), the spec transforms
    through 1/scale — which differs from in/out whenever
    out = floor(in*scale) truncates (e.g. in=3, scale=2.6 -> out=7,
    1/2.6 != 3/7); using the wrong ratio picks wrong source pixels."""
    i = np.arange(out_size, dtype=np.float64)
    ratio = (1.0 / scale) if scale is not None else in_size / out_size
    if coord == "asymmetric":
        return i * ratio
    if coord in ("half_pixel", "pytorch_half_pixel"):
        x = (i + 0.5) * ratio - 0.5
        if coord == "pytorch_half_pixel" and out_size == 1:
            x = np.zeros_like(x)
        return x
    if coord == "align_corners":
        if out_size == 1:
            return np.zeros_like(i)
        return i * (in_size - 1) / (out_size - 1)
    raise OnnxImportError(
        f"Resize coordinate_transformation_mode {coord!r} not supported")


def _nearest_round(x_orig, nearest_mode):
    if nearest_mode == "floor":
        return np.floor(x_orig)
    if nearest_mode == "ceil":
        return np.ceil(x_orig)
    if nearest_mode == "round_prefer_ceil":
        return np.floor(x_orig + 0.5)
    # spec default: round_prefer_floor (round-half-down)
    return np.ceil(x_orig - 0.5)


def _resize_axis_nearest(ctx, v, axis, in_size, out_size, coord,
                         nearest_mode, prefix, scale=None):
    x_orig = _resize_src_coords(out_size, in_size, coord, scale)
    idx = np.clip(_nearest_round(x_orig, nearest_mode),
                  0, in_size - 1).astype(np.int32)
    c = ctx.sd.constant(f"{prefix}_nidx{axis}", idx)
    return ctx.op("gather", [v, c], axis=axis)


def _resize_axis_linear(ctx, v, axis, in_size, out_size, coord, prefix,
                        ndim=4, scale=None):
    x_orig = np.clip(_resize_src_coords(out_size, in_size, coord, scale),
                     0, in_size - 1)
    lo = np.floor(x_orig)
    frac = (x_orig - lo).astype(np.float32)
    hi = np.minimum(lo + 1, in_size - 1).astype(np.int32)
    lo = lo.astype(np.int32)
    wshape = [1] * ndim
    wshape[axis] = out_size
    glo = ctx.op("gather", [v, ctx.sd.constant(f"{prefix}_llo{axis}", lo)],
                 axis=axis)
    ghi = ctx.op("gather", [v, ctx.sd.constant(f"{prefix}_lhi{axis}", hi)],
                 axis=axis)
    w1 = ctx.sd.constant(f"{prefix}_lw1{axis}",
                         (1.0 - frac).reshape(wshape))
    w2 = ctx.sd.constant(f"{prefix}_lw2{axis}", frac.reshape(wshape))
    return ctx.op("add", [ctx.op("mul", [glo, w1]),
                          ctx.op("mul", [ghi, w2])])


@R("Resize", "Upsample")
def _resize(ctx):
    """Exact per-coordinate-mode resize: nearest (all nearest_modes,
    asymmetric/half_pixel/align_corners) and linear (asymmetric incl.
    the opset-9 Upsample semantics, half_pixel, align_corners), lowered
    to static gather indices + separable lerp weights computed at
    import time (XLA static-shape discipline; the half_pixel linear
    case keeps the fused resize_bilinear kernel). Loud elsewhere
    (cubic, dynamic scales)."""
    mode = ctx.attr("mode", "nearest")
    # Upsample (opset <=9) predates coordinate_transformation_mode:
    # its fixed semantics are asymmetric coords + floor rounding
    if ctx.node.op_type == "Upsample":
        coord, nearest_mode = "asymmetric", "floor"
    else:
        coord = ctx.attr("coordinate_transformation_mode", "half_pixel")
        nearest_mode = ctx.attr("nearest_mode", "round_prefer_floor")
    # scales: Upsample/opset10 input 1; Resize opset>=11 input 2 (roi=1)
    scales = sizes = None
    if ctx.node.op_type == "Upsample":
        scales = ctx.static_np(1)
    else:
        s = ctx.maybe_static(2)
        if s is not None and np.asarray(s).size:
            scales = s
        elif len(ctx.inputs) > 3:
            sizes = ctx.static_np(3)
        else:
            raise OnnxImportError(
                f"{ctx.node.name}: Resize needs static scales or a "
                "sizes input (dynamic scales not importable)")

    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is None:
        raise OnnxImportError(
            f"{ctx.node.name}: Resize needs a known input shape")
    in_h, in_w = int(aval.shape[2]), int(aval.shape[3])
    sc_h = sc_w = None  # provided scale factors (None when sizes given)
    if sizes is not None:
        out_h, out_w = [int(v) for v in np.asarray(sizes).ravel()[2:]]
    else:
        sc = [float(v) for v in np.asarray(scales).ravel()]
        if len(sc) != 4 or sc[0] != 1 or sc[1] != 1:
            raise OnnxImportError(
                f"{ctx.node.name}: Resize scales must be [1,1,sH,sW]")
        # spec: output dim = floor(input_dim * scale); the coordinate
        # transform still uses 1/scale, NOT in/out (they differ when
        # the floor truncates)
        sc_h, sc_w = sc[2], sc[3]
        out_h = int(np.floor(in_h * sc_h))
        out_w = int(np.floor(in_w * sc_w))

    name = ctx.node.output[0]
    if mode == "nearest":
        # integer-upsample fast path: repeat equals exactly the two
        # diagonal pairs (asymmetric+floor, half_pixel+round_prefer_
        # floor) for WHOLE scale factors; the CROSS pairs differ (e.g.
        # half_pixel+floor at scale 2 picks [0,0,0,1], not repeat),
        # and a fractional provided scale (2.4 -> out%in==0 by luck)
        # must not silently become a plain repeat
        whole = (sc_h is None or sc_h == int(sc_h)) \
            and (sc_w is None or sc_w == int(sc_w)) \
            and out_h % in_h == 0 and out_w % in_w == 0
        if whole and (coord, nearest_mode) in (
                ("asymmetric", "floor"),
                ("half_pixel", "round_prefer_floor")):
            x = ctx.to_nhwc(ctx.inputs[0])
            out = ctx.op("upsampling2d", [x],
                         scale=(out_h // in_h, out_w // in_w))
            return ctx.to_nchw(out)
        v = _resize_axis_nearest(ctx, ctx.inputs[0], 2, in_h, out_h,
                                 coord, nearest_mode, name, sc_h)
        return _resize_axis_nearest(ctx, v, 3, in_w, out_w, coord,
                                    nearest_mode, name, sc_w)
    if mode == "linear":
        # the fused resize_bilinear kernel transforms through in/out;
        # valid only when that equals the spec ratio (sizes given, or
        # scales that divide exactly) and the coord mode is half_pixel
        exact_ratio = (sc_h is None or in_h * sc_h == out_h) \
            and (sc_w is None or in_w * sc_w == out_w)
        if exact_ratio and (
                coord == "half_pixel"
                or (coord == "pytorch_half_pixel"
                    and out_h > 1 and out_w > 1)):
            x = ctx.to_nhwc(ctx.inputs[0])
            out = ctx.op("resize_bilinear", [x], size=[out_h, out_w])
            return ctx.to_nchw(out)
        v = _resize_axis_linear(ctx, ctx.inputs[0], 2, in_h, out_h,
                                coord, name, scale=sc_h)
        return _resize_axis_linear(ctx, v, 3, in_w, out_w, coord, name,
                                   scale=sc_w)
    raise OnnxImportError(
        f"{ctx.node.name}: Resize mode {mode!r} not supported")


@R("InstanceNormalization")
def _instance_norm(ctx):
    x = ctx.to_nhwc(ctx.inputs[0])
    out = ctx.op("instance_norm", [x, ctx.inputs[1], ctx.inputs[2]],
                 eps=float(ctx.attr("epsilon", 1e-5)))
    return ctx.to_nchw(out)


@R("TopK")
def _topk(ctx):
    k = int(ctx.static_np(1).ravel()[0])
    axis = int(ctx.attr("axis", -1))
    largest = int(ctx.attr("largest", 1))
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    rank = len(aval.shape) if aval is not None else None
    if axis not in (-1, (rank - 1 if rank else -1)):
        raise OnnxImportError(
            f"{ctx.node.name}: TopK on non-last axis not supported")
    x = ctx.inputs[0]
    if not largest:
        x = ctx.op("neg", [x])
    vals, idx = ctx.op("top_k", [x], n_out=2, k=k)
    if not largest:
        vals = ctx.op("neg", [vals])
    return vals, idx


@R("CumSum")
def _cumsum(ctx):
    axis = int(ctx.static_np(1).ravel()[0])
    return ctx.op("cumsum", ctx.inputs[:1], axis=axis,
                  exclusive=bool(ctx.attr("exclusive", 0)),
                  reverse=bool(ctx.attr("reverse", 0)))


@R("Range")
def _range(ctx):
    start, limit, delta = (ctx.static_np(i).ravel()[0] for i in range(3))
    if any(np.issubdtype(np.asarray(v).dtype, np.floating)
           for v in (start, limit, delta)):
        vals = np.arange(float(start), float(limit), float(delta),
                         dtype=np.float32)
    else:
        vals = np.arange(int(start), int(limit), int(delta),
                         dtype=np.int32)
    return ctx.sd.constant(ctx.node.output[0], vals)


@R("OneHot")
def _one_hot(ctx):
    depth = int(ctx.static_np(1).ravel()[0])
    values = np.asarray(ctx.static_np(2)).ravel()   # [off, on]
    ids = ctx.op("cast", ctx.inputs[:1], dtype="int32")
    return ctx.op("one_hot", [ids], depth=depth,
                  axis=int(ctx.attr("axis", -1)),
                  off_value=float(values[0]), on_value=float(values[1]))


@R("GatherND")
def _gather_nd(ctx):
    if int(ctx.attr("batch_dims", 0)) != 0:
        raise OnnxImportError(
            f"{ctx.node.name}: GatherND batch_dims != 0 not supported")
    return ctx.op("gather_nd", ctx.inputs[:2])


@R("GatherElements")
def _gather_elements(ctx):
    return ctx.op("take_along_axis", ctx.inputs[:2],
                  axis=int(ctx.attr("axis", 0)))


@R("ScatterND")
def _scatter_nd(ctx):
    if ctx.attr("reduction", "none") != "none":
        raise OnnxImportError(
            f"{ctx.node.name}: ScatterND reduction not supported")
    return ctx.op("scatter_nd_update", ctx.inputs[:3])


# ReduceL1/L2/LogSumExp have direct registered counterparts — extend
# the same axes-attr-or-input extraction the core _REDUCE loop uses
for _onnx_name, _our in {"ReduceL1": "reduce_norm1",
                         "ReduceL2": "reduce_norm2",
                         "ReduceLogSumExp": "reduce_logsumexp"}.items():
    @R(_onnx_name)
    def _reduce_direct(ctx, _o=_our):
        return ctx.op(_o, ctx.inputs[:1], **_reduce_kwargs(ctx))


@R("ReduceSumSquare", "ReduceLogSum")
def _reduce_composite(ctx):
    kw = _reduce_kwargs(ctx)
    x = ctx.inputs[0]
    if ctx.node.op_type == "ReduceSumSquare":
        return ctx.op("reduce_sum", [ctx.op("mul", [x, x])], **kw)
    return ctx.op("log", [ctx.op("reduce_sum", [x], **kw)])


@R("DepthToSpace", "SpaceToDepth")
def _d2s_s2d(ctx):
    if ctx.node.op_type == "DepthToSpace" \
            and ctx.attr("mode", "DCR") != "DCR":
        raise OnnxImportError(
            f"{ctx.node.name}: DepthToSpace CRD mode not supported")
    our = ("depth_to_space" if ctx.node.op_type == "DepthToSpace"
           else "space_to_depth")
    x = ctx.to_nhwc(ctx.inputs[0])
    out = ctx.op(our, [x], block_size=int(ctx.attr("blocksize")))
    return ctx.to_nchw(out)


@R("HardSwish")
def _hard_swish(ctx):
    return ctx.op("hard_swish", ctx.inputs[:1])


@R("Mish")
def _mish(ctx):
    return ctx.op("mish", ctx.inputs[:1])


@R("Trilu")
def _trilu(ctx):
    k = 0
    if len(ctx.inputs) > 1 and ctx.inputs[1] is not None:
        k = int(ctx.static_np(1).ravel()[0])
    our = "triu" if int(ctx.attr("upper", 1)) else "tril"
    return ctx.op(our, ctx.inputs[:1], k=k)


@R("Einsum")
def _einsum(ctx):
    return ctx.op("einsum", ctx.inputs,
                  equation=ctx.attr("equation"))


@R("ReverseSequence")
def _reverse_sequence(ctx):
    return ctx.op("reverse_sequence", ctx.inputs[:2],
                  seq_axis=int(ctx.attr("time_axis", 0)),
                  batch_axis=int(ctx.attr("batch_axis", 1)))


@R("Mean")
def _mean_nary(ctx):
    out = ctx.inputs[0]
    for v in ctx.inputs[1:]:
        out = ctx.op("add", [out, v])
    inv = ctx.sd.constant(f"{ctx.node.output[0]}_invn",
                          np.float32(1.0 / len(ctx.inputs)))
    return ctx.op("mul", [out, inv])


# ------------------------------------------------ breadth (round 4, pt 2)
@R("Celu")
def _celu(ctx):
    return ctx.op("celu", ctx.inputs[:1],
                  alpha=float(ctx.attr("alpha", 1.0)))


@R("Shrink")
def _shrink(ctx):
    return ctx.op("shrink", ctx.inputs[:1],
                  lambd=float(ctx.attr("lambd", 0.5)),
                  bias=float(ctx.attr("bias", 0.0)))


@R("Hardmax")
def _hardmax(ctx):
    return _opset13_axis_family(ctx, "hardmax")


@R("LpNormalization")
def _lp_normalization(ctx):
    axis = int(ctx.attr("axis", -1))
    p = int(ctx.attr("p", 2))
    if p == 2:
        return ctx.op("l2_normalize", ctx.inputs[:1], axis=axis)
    if p != 1:
        raise OnnxImportError(
            f"{ctx.node.name}: LpNormalization supports p=1 or 2, "
            f"got {p}")
    norm = ctx.op("reduce_sum", [ctx.op("abs", ctx.inputs[:1])],
                  dimensions=[axis], keep_dims=True)
    return ctx.op("div", [ctx.inputs[0], norm])


@R("MeanVarianceNormalization")
def _mvn(ctx):
    axes = ctx.attr("axes", [0, 2, 3])
    return ctx.op("mean_variance_norm", ctx.inputs[:1],
                  axes=tuple(int(a) for a in axes))


# ONNX TensorProto.DataType enum -> numpy (supported subset; unknown
# enums raise loudly per the importer's convention)
_EYE_DT = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
           5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
           10: np.float16, 11: np.float64, 12: np.uint32,
           13: np.uint64}


@R("EyeLike")
def _eye_like(ctx):
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is None or len(aval.shape) != 2:
        raise OnnxImportError(
            f"{ctx.node.name}: EyeLike needs a known 2-D input shape")
    k = int(ctx.attr("k", 0))
    dt_attr = ctx.attr("dtype")
    # ONNX TensorProto.DataType enum; default = input dtype
    if dt_attr is not None:
        if int(dt_attr) not in _EYE_DT:
            raise OnnxImportError(
                f"{ctx.node.name}: EyeLike dtype enum {int(dt_attr)} "
                "not supported (loud-by-convention: silently casting "
                "would corrupt results)")
        dtype = _EYE_DT[int(dt_attr)]
    else:
        dtype = np.dtype(aval.dtype)
    return ctx.sd.constant(
        ctx.node.output[0] + "_eye",
        np.eye(aval.shape[0], aval.shape[1], k, dtype=dtype))


@R("BitShift")
def _bit_shift(ctx):
    d = ctx.attr("direction")
    if d not in ("LEFT", "RIGHT"):
        raise OnnxImportError(
            f"{ctx.node.name}: BitShift direction must be LEFT/RIGHT")
    our = "shift_left" if d == "LEFT" else "shift_right"
    return ctx.op(our, ctx.inputs[:2])


@R("Det")
def _det(ctx):
    return ctx.op("matrix_determinant", ctx.inputs[:1])


@R("LpPool")
def _lp_pool(ctx):
    k = [int(v) for v in ctx.attr("kernel_shape")]
    strides = [int(v) for v in ctx.attr("strides", [1] * len(k))]
    pads = [int(v) for v in ctx.attr("pads", [0] * 2 * len(k))]
    dil = [int(v) for v in ctx.attr("dilations", [1] * len(k))]
    if any(pads) or int(ctx.attr("ceil_mode", 0)) \
            or any(d != 1 for d in dil):
        raise OnnxImportError(
            f"{ctx.node.name}: LpPool with explicit pads, ceil_mode or "
            "dilations not supported")
    p = int(ctx.attr("p", 2))
    x = ctx.to_nhwc(ctx.inputs[0])
    out = ctx.op("pnormpool2d", [x], kernel=tuple(k),
                 strides=tuple(strides), padding="VALID", p=p)
    return ctx.to_nchw(out)


@R("GlobalLpPool")
def _global_lp_pool(ctx):
    # spec: (sum |x|^p)^(1/p) over ALL dims from 2 on (N,C,spatial...)
    # — the ABS matters for odd p on negative inputs
    aval = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
    if aval is None:
        raise OnnxImportError(
            f"{ctx.node.name}: GlobalLpPool needs a known input rank")
    p = int(ctx.attr("p", 2))
    powed = ctx.op("pow", [ctx.op("abs", ctx.inputs[:1]),
                           ctx.sd.constant(ctx.node.output[0] + "_p",
                                           np.float32(p))])
    s = ctx.op("reduce_sum", [powed],
               dimensions=list(range(2, len(aval.shape))), keep_dims=True)
    return ctx.op("pow", [s, ctx.sd.constant(
        ctx.node.output[0] + "_ip", np.float32(1.0 / p))])


@R("GridSample")
def _grid_sample(ctx):
    mode = ctx.attr("mode", "bilinear")
    if mode == "linear":  # opset-20 rename
        mode = "bilinear"
    pad = ctx.attr("padding_mode", "zeros")
    if mode not in ("bilinear", "nearest") or pad not in ("zeros",
                                                          "border"):
        raise OnnxImportError(
            f"{ctx.node.name}: GridSample mode={mode!r}/"
            f"padding_mode={pad!r} not supported")
    x = ctx.to_nhwc(ctx.inputs[0])
    out = ctx.op("grid_sample", [x, ctx.inputs[1]], mode=mode,
                 padding_mode=pad,
                 align_corners=bool(ctx.attr("align_corners", 0)))
    return ctx.to_nchw(out)


@R("DequantizeLinear")
def _dequantize_linear(ctx):
    ins = [v for v in ctx.inputs[:3] if v is not None]
    return ctx.op("dequantize_linear", ins,
                  axis=int(ctx.attr("axis", 1)))


@R("QuantizeLinear")
def _quantize_linear(ctx):
    ins = [v for v in ctx.inputs[:3] if v is not None]
    # output range follows the zero-point dtype (spec default uint8
    # when omitted); the dtype is knowable from avals even when the
    # value itself is not a static initializer
    zp_dtype = None
    zp = ctx.maybe_static(2)
    if zp is not None:
        zp_dtype = zp.dtype
    elif len(ctx.inputs) > 2 and ctx.inputs[2] is not None and ctx.avals:
        aval = ctx.avals.get(ctx.inputs[2].name)
        zp_dtype = np.dtype(aval.dtype) if aval is not None else None
    qmin, qmax = (-128, 127) if zp_dtype == np.int8 else (0, 255)
    return ctx.op("quantize_linear", ins, axis=int(ctx.attr("axis", 1)),
                  qmin=qmin, qmax=qmax)


# ---------------------------------------------------------------- import
def _propagate_onnx(sd, const_vals, avals, from_idx: int) -> None:
    """Shape/dtype eval for ops emitted since from_idx, plus eager
    folding of small integer results whose inputs are all import-time
    constants (the exporter's Shape->Gather->Concat reshape subgraphs
    become consts Reshape can consume)."""
    import jax

    from deeplearning4j_tpu.ops.registry import get_op

    for opnode in sd._ops[from_idx:]:
        fn = get_op(opnode.op_name)
        ins = []
        for iname in opnode.inputs:
            if iname in avals:
                ins.append(avals[iname])
            elif iname in sd._arrays:
                a = sd._arrays[iname]
                ins.append(jax.ShapeDtypeStruct(tuple(a.shape), a.dtype))
            else:
                ins = None
                break
        if ins is None:
            continue
        try:
            out = jax.eval_shape(
                lambda *a: fn(*a, **opnode.attrs), *ins)
        except Exception:
            continue
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        for k, on in enumerate(opnode.outputs):
            if k < len(outs):
                avals[on] = outs[k]
        if (len(opnode.outputs) == 1
                and (np.issubdtype(outs[0].dtype, np.integer)
                     or outs[0].dtype == np.bool_)
                and int(np.prod(outs[0].shape, dtype=np.int64)) <= 256):
            # bools ride the int fold: shape-selection chains like
            # ConstantOfShape->Mul->Equal->Where (torch ViT's
            # expand-shape idiom) break without the Equal link
            vals = []
            for iname in opnode.inputs:
                v = const_vals.get(iname)
                if v is None and iname in sd._arrays:
                    v = np.asarray(sd._arrays[iname])
                if v is None:
                    vals = None
                    break
                vals.append(v)
            if vals is not None:
                try:
                    # x64 on: jnp would truncate the INT64_MAX
                    # slice-end sentinels flowing through these folds
                    # to int32 (-1 = drop-last-element)
                    with jax.enable_x64():
                        const_vals[opnode.outputs[0]] = \
                            np.asarray(fn(*vals, **opnode.attrs))
                except Exception:
                    pass


def _walk_onnx_nodes(sd, nodes, tensors, const_vals, avals,
                     resolve_outer=None) -> None:
    """The node walk, reusable for the top graph AND for If/Loop
    sub-graphs (resolve_outer supplies outer-scope captures — ONNX
    sub-graphs reference enclosing tensors by name)."""
    import jax

    for node in nodes:
        ins: List[Optional[SDVariable]] = []
        statics: List[Optional[np.ndarray]] = []
        for ref in node.input:
            if ref == "":
                ins.append(None)
                statics.append(None)
                continue
            if ref not in tensors and resolve_outer is not None:
                v = resolve_outer(ref)
                if v is not None:
                    tensors[ref] = v
            if ref not in tensors:
                raise OnnxImportError(
                    f"node {node.name or node.op_type}: unresolved "
                    f"input {ref!r}")
            ins.append(tensors[ref])
            statics.append(const_vals.get(ref))
        if node.op_type in ("If", "Loop"):
            mapper_trace.record("onnx", node.op_type)
            handler = _handle_if if node.op_type == "If" else _handle_loop
            out = handler(sd, node, tensors, const_vals, avals, ins,
                          resolve_outer)
            n_ops_before = len(sd._ops)
        else:
            mapper = OnnxOpMappingRegistry.get(node.op_type)
            n_ops_before = len(sd._ops)
            out = mapper(_Ctx(sd, node, ins, statics, avals=avals))
        outs = out if isinstance(out, tuple) else (out,)
        for name, v in zip(node.output, outs):
            if v.name != name:
                v.rename(name)
            tensors[name] = v
            # track import-time-computable constants: Constant nodes
            # AND constants materialized by mappers (Shape). Constant
            # values come from the RAW proto attribute — sd._arrays
            # holds jnp arrays, which truncate int64 to int32 (x64
            # off) and would turn INT64_MAX slice sentinels into -1
            if node.op_type == "Constant":
                val = np.asarray(node.attributes.get("value"))
            elif v.name in sd._arrays:
                val = np.asarray(sd._arrays[v.name])
            else:
                val = None
            if val is not None:
                const_vals.setdefault(name, val)
                avals[v.name] = jax.ShapeDtypeStruct(
                    tuple(val.shape), val.dtype)
        _propagate_onnx(sd, const_vals, avals, n_ops_before)


def _import_onnx_subgraph(g, outer, capture_index, capture_base,
                          formal_start=0, parent_resolve=None,
                          build_dict=True, formal_avals=None,
                          outer_avals=None):
    """Import a GraphProto as a serialized sub-graph dict.

    outer = (tensors, const_vals) of the ENCLOSING scope; referenced
    outer names either bake in (constants) or become capture
    placeholders at slot capture_base + capture_index[name] — the
    SHARED capture_index lets If's two branches agree on operand
    order. formal_avals (aligned with g.inputs) and outer_avals (the
    enclosing scope's aval map, consulted for captures) seed shape
    inference inside the sub-graph — Loop scan outputs need the
    element shape to pre-allocate their stacked buffer.
    Returns (dict, (sub, tensors, avals))."""
    from deeplearning4j_tpu.autodiff.control_flow import (
        ARG_PREFIX, subgraph_to_dict,
    )

    o_tensors, o_consts = outer
    sub = SameDiff.create()
    tensors: Dict[str, SDVariable] = {}
    const_vals: Dict[str, np.ndarray] = {}
    avals: Dict[str, Any] = {}
    for k, vi in enumerate(g.inputs):
        ph = sub.placeholder(f"{ARG_PREFIX}{formal_start + k}")
        tensors[vi.name] = ph
        if formal_avals is not None and k < len(formal_avals) \
                and formal_avals[k] is not None:
            avals[ph.name] = formal_avals[k]
    for init in g.initializers:
        arr = init.to_numpy()
        const_vals[init.name] = arr
        tensors[init.name] = sub.constant(init.name, arr)

    def resolve_outer(ref):
        if ref not in o_tensors and ref not in o_consts \
                and parent_resolve is not None:
            # grand-outer reference (If inside Loop etc.): let the
            # enclosing scope capture it first, then capture from there
            pv = parent_resolve(ref)
            if pv is not None:
                o_tensors[ref] = pv
        if ref in o_consts:
            # outer constants bake in, so static-operand mappers
            # (axes, shapes) keep working inside the sub-graph
            arr = np.asarray(o_consts[ref])
            const_vals[ref] = arr
            return sub.constant(ref, arr)
        if ref in o_tensors:
            if ref not in capture_index:
                capture_index[ref] = len(capture_index)
            ph = sub.placeholder(
                f"{ARG_PREFIX}{capture_base + capture_index[ref]}")
            if outer_avals is not None:
                av = outer_avals.get(o_tensors[ref].name)
                if av is not None:
                    avals[ph.name] = av
            return ph
        return None

    _walk_onnx_nodes(sub, g.nodes, tensors, const_vals, avals,
                     resolve_outer)
    outs = []
    for o in g.outputs:
        if o.name not in tensors:
            raise OnnxImportError(
                f"sub-graph output {o.name!r} not produced")
        outs.append(tensors[o.name].name)
    if not build_dict:
        return None, (sub, tensors, avals)
    d = subgraph_to_dict(sub, outs, capture_base + len(capture_index))
    return d, (sub, tensors, avals)


def _handle_if(sd, node, tensors, const_vals, avals, ins,
               resolve_outer):
    """ONNX If → if_cond: branches have no formal inputs; every outer
    reference becomes a shared capture operand."""
    then_g = node.attributes.get("then_branch")
    else_g = node.attributes.get("else_branch")
    if then_g is None or else_g is None:
        raise OnnxImportError(f"{node.name or 'If'}: missing branch")
    caps: Dict[str, int] = {}
    outer = (tensors, const_vals)
    then_d, _ = _import_onnx_subgraph(then_g, outer, caps,
                                      capture_base=0,
                                      parent_resolve=resolve_outer,
                                      outer_avals=avals)
    else_d, _ = _import_onnx_subgraph(else_g, outer, caps,
                                      capture_base=0,
                                      parent_resolve=resolve_outer,
                                      outer_avals=avals)
    then_d["n_in"] = else_d["n_in"] = len(caps)
    ordered = sorted(caps, key=caps.get)
    operands = [ins[0].name] + [tensors[n].name for n in ordered]
    return sd._op("if_cond", operands, n_out=len(node.output),
                  name=node.output[0], true_graph=then_d,
                  false_graph=else_d)


def _handle_loop(sd, node, tensors, const_vals, avals, ins,
                 resolve_outer):
    """ONNX Loop → while_loop. State = (iter, cond, carried...,
    captures..., M, scan_buffers...).

    Scan outputs (per-iteration values stacked along a new axis 0) use
    the dense-TensorArray pattern: each becomes a pre-allocated
    ``[trips, *elem]`` buffer carried as loop state, written at the
    iteration index each step — which requires a STATICALLY BOUNDED
    loop (XLA needs the buffer shape at compile time), so scan outputs
    on a dynamically-terminated Loop stay a loud error. If the loop
    exits early, trailing rows keep their zero init (the ONNX
    dynamic-length semantics can't exist under static shapes; counted
    for-loops — the pattern every real exporter emits — are exact)."""
    from deeplearning4j_tpu.autodiff.control_flow import (
        ARG_PREFIX, derive_trip_count, subgraph_to_dict,
    )

    body_g = node.attributes.get("body")
    if body_g is None:
        raise OnnxImportError(f"{node.name or 'Loop'}: missing body")
    carried = ins[2:]
    n_carried = len(carried)
    n_scan = len(node.output) - n_carried
    if n_scan < 0:
        raise OnnxImportError(
            f"{node.name or 'Loop'}: {len(node.output)} outputs < "
            f"{n_carried} carried values")
    n_formal = len(body_g.inputs)          # iter, cond, carried...
    if n_formal != 2 + n_carried:
        raise OnnxImportError(
            f"{node.name or 'Loop'}: body takes {n_formal} inputs, "
            f"expected {2 + n_carried}")
    if len(body_g.outputs) != 1 + n_carried + n_scan:
        raise OnnxImportError(
            f"{node.name or 'Loop'}: body returns "
            f"{len(body_g.outputs)} values, expected "
            f"{1 + n_carried + n_scan} (cond + carried + scan)")
    import jax

    caps: Dict[str, int] = {}
    formal_avals = [jax.ShapeDtypeStruct((), np.int32),
                    jax.ShapeDtypeStruct((), np.bool_)]
    for v in carried:
        formal_avals.append(avals.get(v.name) if v is not None
                            else None)
    _, (sub, sub_tensors, sub_avals) = _import_onnx_subgraph(
        body_g, (tensors, const_vals), caps, capture_base=n_formal,
        parent_resolve=resolve_outer, build_dict=False,
        formal_avals=formal_avals, outer_avals=avals)
    n_caps = len(caps)
    m_slot = n_formal + n_caps             # trip count rides here
    n_state = m_slot + 1 + n_scan          # ... then scan buffers

    # body must return the FULL state: iter+1, cond_out, carried_out,
    # captures (pass-through), M (pass-through), buffers (written at
    # the CURRENT iteration index)
    it_ph = sub._vars[f"{ARG_PREFIX}0"]
    one = sub.constant("loop_one", np.int32(1))
    it_next = sub._op("add", [it_ph.name, one.name])
    body_outs = [it_next.name]
    for o in body_g.outputs[:1 + n_carried]:
        if o.name not in sub_tensors:
            raise OnnxImportError(
                f"Loop body output {o.name!r} not produced")
        body_outs.append(sub_tensors[o.name].name)
    for slot in range(n_formal, m_slot + 1):
        phn = f"{ARG_PREFIX}{slot}"
        if phn not in sub._vars:
            sub.placeholder(phn)
        body_outs.append(phn)
    scan_avals = []
    for k in range(n_scan):
        o = body_g.outputs[1 + n_carried + k]
        if o.name not in sub_tensors:
            raise OnnxImportError(
                f"Loop scan output {o.name!r} not produced")
        av = sub_avals.get(sub_tensors[o.name].name)
        if av is None:
            raise OnnxImportError(
                f"{node.name or 'Loop'}: cannot infer the element "
                f"shape of scan output {o.name!r} (needed to "
                "pre-allocate the stacked buffer)")
        scan_avals.append(av)
        buf_ph = sub.placeholder(f"{ARG_PREFIX}{m_slot + 1 + k}")
        written = sub._op("tensorarray_write",
                          [buf_ph.name, it_ph.name,
                           sub_tensors[o.name].name])
        body_outs.append(written.name)
    body_full = subgraph_to_dict(sub, body_outs, n_state)

    # cond: iter < M (when given) AND carried cond (when given)
    csub = SameDiff.create()
    c_it = csub.placeholder(f"{ARG_PREFIX}0")
    c_cond = csub.placeholder(f"{ARG_PREFIX}1")
    have_m = ins[0] is not None
    have_cond = ins[1] is not None
    if have_m:
        c_m = csub.placeholder(f"{ARG_PREFIX}{m_slot}")
        lt = csub._op("lt", [c_it.name, c_m.name])
    if have_m and have_cond:
        pred = csub._op("logical_and", [lt.name, c_cond.name])
    elif have_m:
        pred = lt
    elif have_cond:
        pred = csub._op("identity", [c_cond.name])
    else:
        raise OnnxImportError(
            f"{node.name or 'Loop'}: neither trip count nor condition")
    cond_full = subgraph_to_dict(csub, [pred.name], n_state)

    zero = sd.constant(f"{node.output[0]}_it0", np.int32(0))
    cond0 = ins[1] if have_cond else sd.constant(
        f"{node.output[0]}_cond0", np.bool_(True))
    m_opnd = ins[0] if have_m else sd.constant(
        f"{node.output[0]}_m0", np.int32(0))
    m_const = None
    if have_m:
        mv = const_vals.get(node.input[0])
        if mv is not None and int(np.asarray(mv)) >= 2 ** 31 - 1:
            # "run forever" trip count (torch exports INT64_MAX for
            # cond-driven while loops) — int32 truncation would turn
            # it into -1 and the loop would never run
            m_opnd = sd.constant(f"{node.output[0]}_minf",
                                 np.int32(2 ** 31 - 2))
        elif mv is not None:
            m_const = np.int32(mv)
    # static trip-count derivation makes the loop train (masked-scan
    # lowering): constant M bounds it directly; torch `while i < N`
    # exports bound it through the carried cond recomputed in the body
    init_consts = [np.int32(0),
                   const_vals.get(node.input[1]) if have_cond
                   else np.bool_(True)]
    init_consts += [const_vals.get(r) for r in node.input[2:]]
    init_consts += [None] * len(caps)
    init_consts += [m_const]
    init_consts += [None] * n_scan
    trips = derive_trip_count(cond_full, body_full, init_consts)
    if n_scan and trips is None:
        raise OnnxImportError(
            f"{node.name or 'Loop'}: scan outputs need a statically "
            "bounded loop (XLA allocates the stacked buffer at compile "
            "time) — this Loop's trip count could not be derived "
            "(dynamic termination)")
    buf_names = []
    for k, av in enumerate(scan_avals):
        buf = sd.constant(
            f"{node.output[0]}_scanbuf{k}",
            np.zeros((trips,) + tuple(av.shape), av.dtype))
        buf_names.append(buf.name)
    operands = ([zero.name, cond0.name]
                + [v.name for v in carried]
                + [tensors[n].name
                   for n in sorted(caps, key=caps.get)]
                + [m_opnd.name] + buf_names)
    out = sd._op("while_loop", operands, n_out=n_state,
                 name=node.output[0] + "_state", cond_graph=cond_full,
                 body_graph=body_full, max_trip_count=trips)
    out = out if isinstance(out, tuple) else (out,)
    return tuple([out[2 + i] for i in range(n_carried)]
                 + [out[m_slot + 1 + k] for k in range(n_scan)])


class OnnxImport:
    """Entry point (reference: OnnxFrameworkImporter#runImport)."""

    @staticmethod
    def importGraph(model_or_path) -> SameDiff:
        import jax

        model = OnnxImport._as_model(model_or_path)
        g: GraphProto = model.graph
        global _ACTIVE_OPSET
        saved_opset = _ACTIVE_OPSET
        sd = SameDiff.create()
        tensors: Dict[str, SDVariable] = {}
        const_vals: Dict[str, np.ndarray] = {}
        # var name -> ShapeDtypeStruct: everything is static (no
        # dynamic_axes), so one abstract eval per op gives Shape
        # folding + int-subgraph constant folding for free
        avals: Dict[str, Any] = {}

        for init in g.initializers:
            arr = init.to_numpy()
            const_vals[init.name] = arr
            tensors[init.name] = sd.constant(init.name, arr)
            avals[init.name] = jax.ShapeDtypeStruct(
                tuple(arr.shape), arr.dtype)
        init_names = {i.name for i in g.initializers}
        for vi in g.inputs:
            if vi.name in init_names:
                continue
            shape = [d if d is not None else -1 for d in vi.shape]
            tensors[vi.name] = sd.placeholder(vi.name, shape=shape or None)
            if shape and all(d >= 0 for d in shape):
                from deeplearning4j_tpu.modelimport.onnx.onnx_proto \
                    import TensorProto
                dt = TensorProto._DTYPES.get(vi.elem_type, np.float32)
                avals[vi.name] = jax.ShapeDtypeStruct(
                    tuple(shape), np.dtype(dt))

        try:
            _ACTIVE_OPSET = int(model.opset_version) or 13
            _walk_onnx_nodes(sd, g.nodes, tensors, const_vals, avals)
        finally:
            _ACTIVE_OPSET = saved_opset
        return sd

    @staticmethod
    def _as_model(src) -> ModelProto:
        if isinstance(src, ModelProto):
            return src
        if isinstance(src, bytes):
            return decode_model(src)
        if isinstance(src, str):
            with open(src, "rb") as f:
                return decode_model(f.read())
        raise OnnxImportError(f"cannot interpret {type(src)} as ONNX model")


__all__ = ["OnnxImport", "OnnxOpMappingRegistry", "OnnxImportError"]
