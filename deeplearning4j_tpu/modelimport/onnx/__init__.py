"""ONNX import (reference: nd4j/samediff-import/samediff-import-onnx —
SURVEY.md §2.14). No `onnx` package needed: the wire format is decoded
directly (onnx_proto) and mapped into SameDiff (onnx_import)."""

from deeplearning4j_tpu.modelimport.onnx.onnx_import import (
    OnnxImport, OnnxImportError, OnnxOpMappingRegistry,
)
from deeplearning4j_tpu.modelimport.onnx.onnx_proto import decode_model

__all__ = ["OnnxImport", "OnnxImportError", "OnnxOpMappingRegistry",
           "decode_model"]
