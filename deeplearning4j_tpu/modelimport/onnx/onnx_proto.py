"""Minimal ONNX protobuf wire-format decoder.

Reference: samediff-import-onnx parses onnx.proto ModelProto via
generated classes (SURVEY.md §2.14). The `onnx` package is not
installed in this environment, so this module decodes the protobuf wire
format directly — only the message fields the importer needs
(ModelProto/GraphProto/NodeProto/AttributeProto/TensorProto/
ValueInfoProto, field numbers from the public onnx.proto3 schema).

Wire format refresher: each field is a varint key `(field_num << 3) |
wire_type`; wire types: 0 varint, 1 fixed64, 2 length-delimited,
5 fixed32. Packed repeated scalars arrive as one length-delimited blob.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np


class OnnxDecodeError(ValueError):
    pass


# ------------------------------------------------------------ wire level
def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if i >= len(buf):
            raise OnnxDecodeError("truncated varint")
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 70:
            raise OnnxDecodeError("varint too long")


def _signed(v: int) -> int:
    """Interpret a 64-bit varint as two's-complement int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, raw_value)."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise OnnxDecodeError(f"unsupported wire type {wt}")
        yield field, wt, v


def _packed_varints(raw: bytes) -> List[int]:
    out, i = [], 0
    while i < len(raw):
        v, i = _read_varint(raw, i)
        out.append(_signed(v))
    return out


# --------------------------------------------------------- message types
@dataclasses.dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = dataclasses.field(default_factory=list)
    data_type: int = 1
    _raw: bytes = b""
    _float_data: List[float] = dataclasses.field(default_factory=list)
    _int32_data: List[int] = dataclasses.field(default_factory=list)
    _int64_data: List[int] = dataclasses.field(default_factory=list)
    _double_data: List[float] = dataclasses.field(default_factory=list)

    #: onnx TensorProto.DataType -> numpy
    _DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
               5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
               10: np.float16, 11: np.float64, 12: np.uint32, 13: np.uint64}

    def to_numpy(self) -> np.ndarray:
        if self.data_type not in self._DTYPES:
            raise OnnxDecodeError(
                f"tensor {self.name!r}: unsupported data_type "
                f"{self.data_type}")
        dt = self._DTYPES[self.data_type]
        if self._raw:
            arr = np.frombuffer(self._raw, dtype=dt)
        elif self._float_data:
            arr = np.asarray(self._float_data, np.float32).astype(dt)
        elif self._int64_data:
            arr = np.asarray(self._int64_data, np.int64).astype(dt)
        elif self._int32_data:
            arr = np.asarray(self._int32_data, np.int32).astype(dt)
        elif self._double_data:
            arr = np.asarray(self._double_data, np.float64).astype(dt)
        else:
            arr = np.zeros(0, dt)
        return arr.reshape(self.dims) if self.dims else arr.reshape(())


@dataclasses.dataclass
class AttributeProto:
    name: str = ""
    type: int = 0        # 1=FLOAT 2=INT 3=STRING 4=TENSOR 5=GRAPH
    #                      6=FLOATS 7=INTS 8=STRINGS 10=GRAPHS
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None
    floats: List[float] = dataclasses.field(default_factory=list)
    ints: List[int] = dataclasses.field(default_factory=list)
    strings: List[bytes] = dataclasses.field(default_factory=list)
    graphs: List["GraphProto"] = dataclasses.field(default_factory=list)

    def value(self) -> Any:
        if self.type == 1:
            return self.f
        if self.type == 2:
            return self.i
        if self.type == 3:
            return self.s.decode(errors="replace")
        if self.type == 4:
            return self.t.to_numpy() if self.t is not None else None
        if self.type == 5:
            return self.g
        if self.type == 6:
            return list(self.floats)
        if self.type == 7:
            return list(self.ints)
        if self.type == 8:
            return [s.decode(errors="replace") for s in self.strings]
        if self.type == 10:
            return list(self.graphs)
        return None


@dataclasses.dataclass
class NodeProto:
    name: str = ""
    op_type: str = ""
    domain: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ValueInfoProto:
    name: str = ""
    elem_type: int = 1
    shape: List[Optional[int]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GraphProto:
    name: str = ""
    nodes: List[NodeProto] = dataclasses.field(default_factory=list)
    initializers: List[TensorProto] = dataclasses.field(default_factory=list)
    inputs: List[ValueInfoProto] = dataclasses.field(default_factory=list)
    outputs: List[ValueInfoProto] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ModelProto:
    ir_version: int = 0
    producer_name: str = ""
    opset_version: int = 0
    graph: Optional[GraphProto] = None


# ----------------------------------------------------------- per message
def _decode_tensor(buf: bytes) -> TensorProto:
    t = TensorProto()
    for field, wt, v in _fields(buf):
        if field == 1:
            if wt == 2:
                t.dims.extend(_packed_varints(v))
            else:
                t.dims.append(_signed(v))
        elif field == 2:
            t.data_type = v
        elif field == 4:
            t._float_data.extend(
                struct.unpack(f"<{len(v) // 4}f", v) if wt == 2
                else (struct.unpack("<f", v)[0],))
        elif field == 5:
            t._int32_data.extend(_packed_varints(v) if wt == 2
                                 else [_signed(v)])
        elif field == 7:
            t._int64_data.extend(_packed_varints(v) if wt == 2
                                 else [_signed(v)])
        elif field == 8:
            t.name = v.decode()
        elif field == 9:
            t._raw = v
        elif field == 10:
            t._double_data.extend(
                struct.unpack(f"<{len(v) // 8}d", v) if wt == 2
                else (struct.unpack("<d", v)[0],))
    return t


def _decode_attribute(buf: bytes) -> AttributeProto:
    a = AttributeProto()
    for field, wt, v in _fields(buf):
        if field == 1:
            a.name = v.decode()
        elif field == 2:
            a.f = struct.unpack("<f", v)[0]
        elif field == 3:
            a.i = _signed(v)
        elif field == 4:
            a.s = v
        elif field == 5:
            a.t = _decode_tensor(v)
        elif field == 6:
            a.g = _decode_graph(v)      # sub-graph (If/Loop/Scan)
        elif field == 11:
            a.graphs.append(_decode_graph(v))
        elif field == 7:
            a.floats.extend(struct.unpack(f"<{len(v) // 4}f", v)
                            if wt == 2 else (struct.unpack("<f", v)[0],))
        elif field == 8:
            a.ints.extend(_packed_varints(v) if wt == 2 else [_signed(v)])
        elif field == 9:
            a.strings.append(v)
        elif field == 20:
            a.type = v
    if a.type == 0:
        # producers may omit type; infer from populated field
        if a.ints:
            a.type = 7
        elif a.floats:
            a.type = 6
        elif a.t is not None:
            a.type = 4
        elif a.g is not None:
            a.type = 5
        elif a.graphs:
            a.type = 10
        elif a.s:
            a.type = 3
    return a


def _decode_node(buf: bytes) -> NodeProto:
    n = NodeProto()
    for field, wt, v in _fields(buf):
        if field == 1:
            n.input.append(v.decode())
        elif field == 2:
            n.output.append(v.decode())
        elif field == 3:
            n.name = v.decode()
        elif field == 4:
            n.op_type = v.decode()
        elif field == 5:
            a = _decode_attribute(v)
            n.attributes[a.name] = a.value()
        elif field == 7:
            n.domain = v.decode()
    return n


def _decode_value_info(buf: bytes) -> ValueInfoProto:
    vi = ValueInfoProto()
    for field, wt, v in _fields(buf):
        if field == 1:
            vi.name = v.decode()
        elif field == 2:  # TypeProto
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:  # tensor_type
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            vi.elem_type = v3
                        elif f3 == 2:  # TensorShapeProto
                            for f4, _w4, v4 in _fields(v3):
                                if f4 == 1:  # Dimension
                                    dim_val: Optional[int] = None
                                    for f5, _w5, v5 in _fields(v4):
                                        if f5 == 1:
                                            dim_val = _signed(v5)
                                    vi.shape.append(dim_val)
    return vi


def _decode_graph(buf: bytes) -> GraphProto:
    g = GraphProto()
    for field, wt, v in _fields(buf):
        if field == 1:
            g.nodes.append(_decode_node(v))
        elif field == 2:
            g.name = v.decode()
        elif field == 5:
            g.initializers.append(_decode_tensor(v))
        elif field == 11:
            g.inputs.append(_decode_value_info(v))
        elif field == 12:
            g.outputs.append(_decode_value_info(v))
    return g


def decode_model(data: bytes) -> ModelProto:
    m = ModelProto()
    for field, wt, v in _fields(data):
        if field == 1:
            m.ir_version = v
        elif field == 2:
            m.producer_name = v.decode()
        elif field == 7:
            m.graph = _decode_graph(v)
        elif field == 8:  # OperatorSetIdProto
            # Only the DEFAULT domain ("" / "ai.onnx") versions the core
            # op set; custom-domain entries (com.microsoft, ...) carry
            # unrelated version numbers and must not bump it.
            dom, ver = "", None
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    dom = v2.decode()
                elif f2 == 2:
                    ver = _signed(v2)
            if ver is not None and dom in ("", "ai.onnx"):
                m.opset_version = max(m.opset_version, ver)
    if m.graph is None:
        raise OnnxDecodeError("no GraphProto in model (not an ONNX file?)")
    return m


__all__ = ["decode_model", "ModelProto", "GraphProto", "NodeProto",
           "TensorProto", "AttributeProto", "ValueInfoProto",
           "OnnxDecodeError"]
