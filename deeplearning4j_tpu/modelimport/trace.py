"""Mapper-dispatch accounting shared by the TF/ONNX/Keras importers.

Reference parity: org/nd4j/autodiff/validation/OpValidation's coverage
accounting (SURVEY.md §4) applied to the import layer (§2.14, §2.32) —
the reference fails the build for registered ops no test exercises;
here every mapper DRIVEN by an actual import records itself, and the
end-of-suite gate (tests/test_zzz_mapper_execution_gate.py) fails for
any registered mapper the suite never drove.

Mechanism mirrors ops/registry.py's op accounting: an in-process set,
merged across test subprocesses via DL4J_TPU_MAPPER_TRACE_FILE (set by
tests/conftest.py), appended at interpreter exit. Keys are
"<framework>:<name>", e.g. "tf:Conv2D", "onnx:Softmax", "keras:Dense".
"""

from __future__ import annotations

import atexit
import os
from typing import Set

_DRIVEN: Set[str] = set()


def record(framework: str, name: str) -> None:
    """Record that the mapper for `name` was dispatched on a real node
    during an import (called from the importers' lookup paths — a
    lexical mention in a test does NOT count)."""
    _DRIVEN.add(f"{framework}:{name}")


def driven_mappers() -> Set[str]:
    """Mappers driven so far in THIS process, merged with any trace
    file written by (sub)processes sharing DL4J_TPU_MAPPER_TRACE_FILE."""
    out = set(_DRIVEN)
    path = os.environ.get("DL4J_TPU_MAPPER_TRACE_FILE")
    if path and os.path.exists(path):
        with open(path) as f:
            out.update(ln.strip() for ln in f if ln.strip())
    return out


@atexit.register
def _dump_trace() -> None:
    path = os.environ.get("DL4J_TPU_MAPPER_TRACE_FILE")
    if path and _DRIVEN:
        try:
            with open(path, "a") as f:
                f.write("\n".join(sorted(_DRIVEN)) + "\n")
        except OSError:
            pass
