"""TensorFlow frozen-graph import (reference: nd4j/samediff-import —
ImportGraph + OpMappingRegistry + per-op mapping rules, and the legacy
org/nd4j/imports/graphmapper/tf/TFGraphMapper. SURVEY.md §2.14)."""

from deeplearning4j_tpu.modelimport.tensorflow.tf_import import (
    OpMappingRegistry, TFGraphMapper,
)

__all__ = ["TFGraphMapper", "OpMappingRegistry"]
