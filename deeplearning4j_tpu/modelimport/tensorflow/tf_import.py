"""Frozen TF GraphDef → SameDiff.

Reference: samediff-import-tensorflow ImportGraph#importGraph walks a
frozen protobuf node-by-node through OpMappingRegistry rules into
SameDiff ops (SURVEY.md §3.4 BERT path). Same architecture here:
a registry of per-TF-op mappers emits nodes into a SameDiff graph,
whose execution then whole-graph-compiles under XLA — the imported
graph runs as ONE executable, not an op-at-a-time interpreter.

Protobuf parsing uses the tensorflow package (host-side only — nothing
of TF touches the accelerator); static operands (axes, shapes, perms)
are resolved from Const nodes at import time, mirroring the
reference's constant-resolution during mapping.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable
from deeplearning4j_tpu.modelimport import trace as mapper_trace


class TFImportError(ValueError):
    pass


_DTYPE_MAP = {
    1: "float32", 2: "float64", 3: "int32", 4: "uint8", 6: "int8",
    9: "int64", 10: "bool", 14: "bfloat16", 19: "float16",
}


def _dtype_name(enum_val: int) -> str:
    return _DTYPE_MAP.get(int(enum_val), "float32")


# Sentinel for an unknown dim inside an import-time partially-known
# integer array (shape-subgraph folding; see _PartialEval). Values in
# [iinfo.min, _DYN_LIMIT] are all "dynamic": DYN is the anonymous one;
# _PartialEval allocates provenance-carrying sentinels above it that
# remember WHICH tensor dim they came from (so Reshape can emit
# copy-input-dim semantics when a target mixes a literal -1 with a
# dynamic batch dim — the transpose_for_scores pattern in real BERT
# graphs).
DYN = np.int64(np.iinfo(np.int64).min + 7)
_DYN_LIMIT = np.int64(np.iinfo(np.int64).min + 10_000_000)


def _is_dyn(v) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype.kind not in "iu":
        return np.zeros(a.shape, bool)
    return a <= _DYN_LIMIT


class _Ctx:
    """Everything a mapper needs for one node."""

    def __init__(self, sd: SameDiff, node, inputs: List[SDVariable],
                 static: List[Optional[np.ndarray]], attrs: Dict[str, Any],
                 pe=None, avals=None):
        self.sd = sd
        self.node = node
        self.inputs = inputs
        self._static = static
        self.attrs = attrs
        self.pe = pe          # _PartialEval (provenance registry) or None
        self.avals = avals    # var name -> (probe2 aval, probe3 aval)

    def resolve_dyn_dim(self, sentinel: int) -> Optional[int]:
        """Map a provenance sentinel to a dim index of THIS node's data
        input whose two-probe extents match the sentinel's source dim
        (i.e. 'copy input dim k'), or None."""
        if self.pe is None or self.avals is None:
            return None
        prov = self.pe.dyn_prov.get(int(sentinel))
        if prov is None:
            return None
        vname, dim = prov
        src = self.avals.get(vname)
        dst = self.avals.get(self.inputs[0].name)
        if src is None or dst is None or dim >= len(src[0].shape):
            return None
        want = (src[0].shape[dim], src[1].shape[dim])
        if want[0] == want[1]:
            return None
        for k, ab in enumerate(zip(dst[0].shape, dst[1].shape)):
            if ab == want:
                return k
        return None

    def static_np(self, i: int) -> np.ndarray:
        """Constant value of input i (axes/shapes/perms must be static —
        XLA static-shape discipline; the reference resolves these from
        Const nodes the same way, plus folded shape subgraphs)."""
        v = self._static[i]
        if v is None or bool(np.any(_is_dyn(v))):
            raise TFImportError(
                f"node {self.node.name} ({self.node.op}): input {i} must "
                "be a constant (dynamic shapes/axes not importable)")
        return v

    def partial_np(self, i: int) -> np.ndarray:
        """Like static_np but tolerates DYN entries (unknown dims) —
        used by Reshape, where one unknown dim becomes -1."""
        v = self._static[i]
        if v is None:
            raise TFImportError(
                f"node {self.node.name} ({self.node.op}): input {i} is "
                "not statically resolvable (even partially)")
        return np.asarray(v)

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def op(self, op_name: str, inputs: Sequence[SDVariable], n_out: int = 1,
           **attrs):
        return self.sd._op(op_name, [v.name for v in inputs], n_out=n_out,
                           name=self.node.name, **attrs)


class OpMappingRegistry:
    """TF op type → mapper fn(ctx) -> SDVariable | tuple (reference:
    OpMappingRegistry + per-op MappingRule sets)."""

    _mappers: Dict[str, Callable[[_Ctx], Any]] = {}

    @classmethod
    def register(cls, *tf_ops: str):
        def deco(fn):
            for name in tf_ops:
                cls._mappers[name] = fn
            return fn
        return deco

    @classmethod
    def get(cls, tf_op: str) -> Callable[[_Ctx], Any]:
        try:
            fn = cls._mappers[tf_op]
        except KeyError:
            raise TFImportError(
                f"no mapper for TF op {tf_op!r} "
                f"(have {len(cls._mappers)}: add one via "
                "OpMappingRegistry.register)") from None
        mapper_trace.record("tf", tf_op)
        return fn

    @classmethod
    def has(cls, tf_op: str) -> bool:
        return tf_op in cls._mappers

    @classmethod
    def coverage(cls) -> List[str]:
        return sorted(cls._mappers)


# ------------------------------------------------------------------ attrs
def _decode_attrs(node) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in node.attr.items():
        field = v.WhichOneof("value")
        if field == "b":
            out[k] = bool(v.b)
        elif field == "i":
            out[k] = int(v.i)
        elif field == "f":
            out[k] = float(v.f)
        elif field == "s":
            out[k] = v.s.decode(errors="replace")
        elif field == "type":
            out[k] = _dtype_name(v.type)
        elif field == "shape":
            out[k] = [d.size for d in v.shape.dim]
        elif field == "tensor":
            out[k] = v.tensor  # decoded lazily by Const mapper
        elif field == "list":
            lst = v.list
            if lst.i:
                out[k] = [int(x) for x in lst.i]
            elif lst.f:
                out[k] = [float(x) for x in lst.f]
            elif lst.s:
                out[k] = [x.decode(errors="replace") for x in lst.s]
            elif lst.b:
                out[k] = [bool(x) for x in lst.b]
            else:
                out[k] = []
    return out


# ---------------------------------------------------------------- mappers
def _register_standard_mappers():
    R = OpMappingRegistry.register

    # elementwise binary
    for tf_op, our in [("Add", "add"), ("AddV2", "add"), ("Sub", "sub"),
                       ("Mul", "mul"), ("RealDiv", "div"), ("Div", "div"),
                       # TF Mod is C-truncation for floats (sign
                       # follows dividend) — NOT python floor-mod
                       # (caught by the mapper battery)
                       ("FloorDiv", "floordiv"), ("Mod", "fmod"),
                       ("FloorMod", "floormod"),
                       ("Pow", "pow_pairwise"), ("Maximum", "maximum"),
                       ("Minimum", "minimum"),
                       ("SquaredDifference", "squared_difference"),
                       ("Equal", "eq"), ("NotEqual", "neq"),
                       ("Greater", "gt"), ("GreaterEqual", "gte"),
                       ("Less", "lt"), ("LessEqual", "lte"),
                       ("LogicalAnd", "logical_and"),
                       ("LogicalOr", "logical_or")]:
        R(tf_op)(lambda ctx, _o=our: ctx.op(_o, ctx.inputs[:2]))

    # elementwise unary
    for tf_op, our in [("Neg", "neg"), ("Exp", "exp"), ("Log", "log"),
                       ("Log1p", "log1p"), ("Sqrt", "sqrt"),
                       ("Rsqrt", "rsqrt"), ("Square", "square"),
                       ("Abs", "abs"), ("Sign", "sign"), ("Floor", "floor"),
                       ("Ceil", "ceil"), ("Round", "round"),
                       ("Relu", "relu"), ("Relu6", "relu6"),
                       ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
                       ("Softplus", "softplus"), ("Softsign", "softsign"),
                       ("Elu", "elu"), ("Selu", "selu"), ("Erf", "erf"),
                       ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
                       ("Sinh", "sinh"), ("Cosh", "cosh"),
                       ("Reciprocal", "reciprocal"),
                       ("LogicalNot", "logical_not"),
                       ("IsNan", "isnan"), ("IsInf", "isinf"),
                       ("StopGradient", "stop_gradient"),
                       ("Identity", "identity"), ("Snapshot", "identity")]:
        R(tf_op)(lambda ctx, _o=our: ctx.op(_o, ctx.inputs[:1]))

    @R("LeakyRelu")
    def _leaky(ctx):
        return ctx.op("leakyrelu", ctx.inputs[:1],
                      alpha=float(ctx.attr("alpha", 0.2)))

    @R("Softmax")
    def _softmax(ctx):
        return ctx.op("softmax", ctx.inputs[:1])

    @R("LogSoftmax")
    def _log_softmax(ctx):
        return ctx.op("log_softmax", ctx.inputs[:1])

    @R("MatMul")
    def _matmul(ctx):
        return ctx.op("matmul", ctx.inputs[:2],
                      transpose_a=bool(ctx.attr("transpose_a", False)),
                      transpose_b=bool(ctx.attr("transpose_b", False)))

    @R("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
    def _batch_matmul(ctx):
        ta = bool(ctx.attr("adj_x", False))
        tb = bool(ctx.attr("adj_y", False))
        return ctx.op("matmul", ctx.inputs[:2],
                      transpose_a=ta, transpose_b=tb)

    @R("BiasAdd")
    def _bias_add(ctx):
        if ctx.attr("data_format", "NHWC") == "NCHW":
            # late binding: _nchw_sandwich is defined below in this
            # same registration scope, before any mapper runs
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op(
                    "add", [xt.name, ctx.inputs[1].name]))
        return ctx.op("add", ctx.inputs[:2])

    @R("AddN")
    def _addn(ctx):
        if len(ctx.inputs) == 1:
            # must emit a fresh variable: importGraph renames the mapper's
            # output to the node name, and renaming the upstream input
            # would corrupt the graph's name table
            return ctx.op("identity", ctx.inputs[:1])
        out = ctx.inputs[0]
        for v in ctx.inputs[1:]:
            out = ctx.sd._op("add", [out.name, v.name])
        return out

    # reductions: axes come from a const input
    for tf_op, our in [("Mean", "reduce_mean"), ("Sum", "reduce_sum"),
                       ("Max", "reduce_max"), ("Min", "reduce_min"),
                       ("Prod", "reduce_prod"), ("All", "reduce_all"),
                       ("Any", "reduce_any")]:
        def _red(ctx, _o=our):
            axes = ctx.static_np(1)
            dims = [int(a) for a in np.atleast_1d(axes)]
            return ctx.op(_o, ctx.inputs[:1], dimensions=dims,
                          keep_dims=bool(ctx.attr("keep_dims", False)))
        R(tf_op)(_red)

    @R("ArgMax")
    def _argmax(ctx):
        axis = int(ctx.static_np(1))
        return ctx.op("argmax", ctx.inputs[:1], dimensions=axis)

    # shape manipulation
    @R("Reshape")
    def _reshape(ctx):
        arr = np.atleast_1d(ctx.partial_np(1)).astype(np.int64)
        shape: List[int] = []
        copy_dims: Dict[int, int] = {}
        unknown = 0
        for pos, val in enumerate(arr.tolist()):
            if bool(_is_dyn(np.int64(val))):
                k = ctx.resolve_dyn_dim(val)
                if k is None:
                    unknown += 1
                    shape.append(-1)
                else:
                    copy_dims[pos] = k
                    shape.append(0)   # placeholder; runtime substitutes
            else:
                if val == -1:
                    unknown += 1
                shape.append(int(val))
        if unknown > 1:
            raise TFImportError(
                f"{ctx.node.name}: Reshape target has {unknown} unknown "
                "dims — at most one (mapped to -1) is importable")
        if copy_dims:
            return ctx.op("reshape", ctx.inputs[:1], shape=shape,
                          copy_dims=copy_dims)
        return ctx.op("reshape", ctx.inputs[:1], shape=shape)

    @R("Transpose")
    def _transpose(ctx):
        perm = [int(p) for p in ctx.static_np(1)]
        return ctx.op("transpose", ctx.inputs[:1], permute=perm)

    @R("ExpandDims")
    def _expand(ctx):
        return ctx.op("expand_dims", ctx.inputs[:1],
                      axis=int(ctx.static_np(1)))

    @R("Squeeze")
    def _squeeze(ctx):
        dims = ctx.attr("squeeze_dims") or ctx.attr("axis") or None
        axis = tuple(dims) if dims else None
        return ctx.op("squeeze", ctx.inputs[:1], axis=axis)

    @R("ConcatV2")
    def _concat(ctx):
        axis = int(ctx.static_np(len(ctx.inputs) - 1))
        return ctx.op("concat", ctx.inputs[:-1], axis=axis)

    @R("Pack")
    def _pack(ctx):
        return ctx.op("stack", ctx.inputs, axis=int(ctx.attr("axis", 0)))

    @R("Unpack")
    def _unpack(ctx):
        n = int(ctx.attr("num"))
        return ctx.op("unstack", ctx.inputs[:1], n_out=n,
                      axis=int(ctx.attr("axis", 0)), num=n)

    @R("Split")
    def _split(ctx):
        axis = int(ctx.static_np(0))
        n = int(ctx.attr("num_split"))
        return ctx.op("split", ctx.inputs[1:2], n_out=n,
                      num_splits=n, axis=axis)

    @R("Tile")
    def _tile(ctx):
        reps = [int(r) for r in ctx.static_np(1)]
        return ctx.op("tile", ctx.inputs[:1], reps=reps)

    @R("Pad", "PadV2")
    def _pad(ctx):
        pads = [[int(a), int(b)] for a, b in ctx.static_np(1)]
        value = (float(ctx.static_np(2))
                 if ctx.node.op == "PadV2" and len(ctx.node.input) > 2
                 else 0.0)
        return ctx.op("pad", ctx.inputs[:1], paddings=pads,
                      constant_value=value)

    @R("Slice")
    def _slice(ctx):
        begin = [int(b) for b in ctx.static_np(1)]
        size = [int(s) for s in ctx.static_np(2)]
        return ctx.op("slice", ctx.inputs[:1], begin=begin, size=size)

    @R("StridedSlice")
    def _strided_slice(ctx):
        if ctx.attr("ellipsis_mask", 0):
            raise TFImportError(
                f"{ctx.node.name}: StridedSlice ellipsis mask "
                "not supported")
        bm = int(ctx.attr("begin_mask", 0))
        em = int(ctx.attr("end_mask", 0))
        sm = int(ctx.attr("shrink_axis_mask", 0))
        nm = int(ctx.attr("new_axis_mask", 0))
        try:
            begin = [int(b) for b in ctx.static_np(1)]
            end = [int(e) for e in ctx.static_np(2)]
            strides = [int(s) for s in ctx.static_np(3)]
        except TFImportError:
            return _strided_slice_dynamic(ctx, bm, em, sm, nm)
        return ctx.op("tf_strided_slice", ctx.inputs[:1], begin=begin,
                      end=end, strides=strides, begin_mask=bm, end_mask=em,
                      shrink_axis_mask=sm, new_axis_mask=nm)

    def _strided_slice_dynamic(ctx, bm, em, sm, nm):
        """Loop-counter indexing (``a[:, i]``, ``a[i]``): begin/end hold
        traced scalars. Supported subset: unit strides, no new-axis,
        dynamic entries only on shrink dims (size-1 runtime index) —
        lowered to lax.dynamic_slice which XLA keeps on-device."""
        if nm:
            raise TFImportError(
                f"{ctx.node.name}: dynamic StridedSlice with "
                "new_axis_mask not supported")
        begin = np.atleast_1d(ctx.partial_np(1)).astype(np.int64)
        end = np.atleast_1d(ctx.partial_np(2)).astype(np.int64)
        strides = np.atleast_1d(ctx.partial_np(3)).astype(np.int64)
        if np.any(_is_dyn(strides)) or not np.all(strides == 1):
            raise TFImportError(
                f"{ctx.node.name}: dynamic StridedSlice requires unit "
                "strides")
        b_spec: List[Optional[int]] = []
        e_spec: List[Optional[int]] = []
        for d in range(len(begin)):
            b_dyn = bool(_is_dyn(begin[d]))
            e_dyn = bool(_is_dyn(end[d]))
            if (b_dyn or e_dyn) and not (sm & (1 << d)) \
                    and not ((bm & (1 << d)) and (em & (1 << d))):
                raise TFImportError(
                    f"{ctx.node.name}: dynamic StridedSlice begin/end "
                    f"at dim {d} without shrink_axis_mask (only size-1 "
                    "runtime indexing is importable)")
            b_spec.append(None if b_dyn else int(begin[d]))
            e_spec.append(None if e_dyn else int(end[d]))
        return ctx.op("tf_strided_slice_dyn",
                      [ctx.inputs[0], ctx.inputs[1]],
                      begin=b_spec, end=e_spec, begin_mask=bm,
                      end_mask=em, shrink_axis_mask=sm)

    @R("GatherV2", "Gather")
    def _gather(ctx):
        axis = int(ctx.static_np(2)) if len(ctx.inputs) > 2 else 0
        return ctx.op("gather", ctx.inputs[:2], axis=axis)

    @R("OneHot")
    def _one_hot(ctx):
        depth = int(ctx.static_np(1))
        on = float(ctx.static_np(2)) if len(ctx.node.input) > 2 else 1.0
        off = float(ctx.static_np(3)) if len(ctx.node.input) > 3 else 0.0
        axis = int(ctx.attr("axis", -1))
        return ctx.op("one_hot", ctx.inputs[:1], depth=depth, on_value=on,
                      off_value=off, axis=axis)

    @R("Cast")
    def _cast(ctx):
        return ctx.op("cast", ctx.inputs[:1], dtype=ctx.attr("DstT"))

    @R("Shape")
    def _shape(ctx):
        return ctx.op("shape_of", ctx.inputs[:1])

    @R("Fill")
    def _fill(ctx):
        dims = [int(d) for d in ctx.static_np(0)]
        value = float(ctx.static_np(1))
        return ctx.op("tf_fill", [], shape=dims, value=value)

    @R("Range")
    def _range(ctx):
        start, limit, delta = (ctx.static_np(i) for i in range(3))
        is_f = any(np.issubdtype(np.asarray(v).dtype, np.floating)
                   for v in (start, limit, delta))
        return ctx.op("range", [],
                      start=float(start), stop=float(limit),
                      step=float(delta),
                      dtype="float32" if is_f else "int32")

    @R("Select", "SelectV2")
    def _select(ctx):
        return ctx.op("where", ctx.inputs[:3])

    # ---- NN ops ----
    # NCHW graphs import via a transpose sandwich: NCHW -> NHWC (the
    # op's native layout here, TPU-preferred) -> NCHW. Between two
    # consecutive NCHW nodes the inner [0,3,1,2]/[0,2,3,1] pair is
    # adjacent in the graph and XLA cancels it, so a whole NCHW conv
    # stack costs two real layout ops total (reference: the importer's
    # permuteFirstDims/NCHW handling in Conv2D MappingRules).
    def _check_rank4(ctx, v, what):
        aval = (ctx.avals or {}).get(v.name)
        if aval is not None and len(aval[0].shape) != 4:
            raise TFImportError(
                f"{ctx.node.name}: {what} expects a rank-4 tensor, got "
                f"rank {len(aval[0].shape)}")

    def _nchw_sandwich(ctx, emit, *extra_inputs):
        """Emit `emit(nhwc_x, *extra)` wrapped in NCHW<->NHWC
        transposes; the final transpose carries the node's name."""
        x = ctx.inputs[0]
        _check_rank4(ctx, x, f"{ctx.node.op} NCHW")
        xt = ctx.sd._op("transpose", [x.name], permute=[0, 2, 3, 1])
        y = emit(xt, *extra_inputs)
        return ctx.op("transpose", [y], permute=[0, 3, 1, 2])

    def _check_padding(ctx, allow_explicit=False):
        """SAME/VALID (+ EXPLICIT for convs) — anything else must not
        be silently treated as VALID."""
        pad = ctx.attr("padding", "VALID")
        ok = ("SAME", "VALID", "EXPLICIT") if allow_explicit \
            else ("SAME", "VALID")
        if pad not in ok:
            raise TFImportError(
                f"{ctx.node.name}: padding={pad!r} not supported "
                f"({'/'.join(ok)} only)")
        return pad

    def _explicit_pairs(ctx, df):
        """TF explicit_paddings: 8 ints, (lo,hi) per dim in data_format
        order. Returns ((h_lo,h_hi),(w_lo,w_hi)); batch/channel pads
        must be zero (TF enforces this too)."""
        ep = [int(v) for v in ctx.attr("explicit_paddings", [])]
        if len(ep) != 8:
            raise TFImportError(
                f"{ctx.node.name}: EXPLICIT padding needs 8 "
                f"explicit_paddings entries, got {len(ep)}")
        pairs = list(zip(ep[0::2], ep[1::2]))
        if df == "NHWC":
            nc, hw = (pairs[0], pairs[3]), (pairs[1], pairs[2])
        else:
            nc, hw = (pairs[0], pairs[1]), (pairs[2], pairs[3])
        if any(v != 0 for q in nc for v in q):
            raise TFImportError(
                f"{ctx.node.name}: nonzero batch/channel explicit "
                "padding is not a convolution")
        return hw

    def _layout(ctx):
        df = ctx.attr("data_format", "NHWC")
        if df not in ("NHWC", "NCHW"):
            raise TFImportError(
                f"{ctx.node.name}: data_format={df!r} not supported")
        return df, ((2, 3) if df == "NCHW" else (1, 2))

    def _conv_pad_attr(ctx, df):
        pad = _check_padding(ctx, allow_explicit=True)
        if pad == "EXPLICIT":
            return _explicit_pairs(ctx, df)
        return "SAME" if pad == "SAME" else (0, 0)

    @R("Conv2D")
    def _conv2d(ctx):
        df, hw = _layout(ctx)
        strides = ctx.attr("strides", [1, 1, 1, 1])
        dil = ctx.attr("dilations", [1, 1, 1, 1])
        kw = dict(strides=(int(strides[hw[0]]), int(strides[hw[1]])),
                  padding=_conv_pad_attr(ctx, df),
                  dilation=(int(dil[hw[0]]), int(dil[hw[1]])))
        if df == "NCHW":
            # TF filters are HWIO for BOTH layouts; only x needs moving
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op(
                    "conv2d", [xt.name, ctx.inputs[1].name], **kw))
        return ctx.op("conv2d", ctx.inputs[:2], **kw)

    @R("DepthwiseConv2dNative")
    def _depthwise(ctx):
        df, hw = _layout(ctx)
        strides = ctx.attr("strides", [1, 1, 1, 1])
        dil = ctx.attr("dilations", [1, 1, 1, 1])
        kw = dict(strides=(int(strides[hw[0]]), int(strides[hw[1]])),
                  padding=_conv_pad_attr(ctx, df),
                  dilation=(int(dil[hw[0]]), int(dil[hw[1]])))
        if df == "NCHW":
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op(
                    "depthwise_conv2d", [xt.name, ctx.inputs[1].name],
                    **kw))
        return ctx.op("depthwise_conv2d", ctx.inputs[:2], **kw)

    @R("MaxPool")
    def _maxpool(ctx):
        df, hw = _layout(ctx)
        ks = ctx.attr("ksize", [1, 2, 2, 1])
        st = ctx.attr("strides", [1, 2, 2, 1])
        pad = _check_padding(ctx)
        kw = dict(kernel=(int(ks[hw[0]]), int(ks[hw[1]])),
                  strides=(int(st[hw[0]]), int(st[hw[1]])),
                  padding="SAME" if pad == "SAME" else "VALID")
        if df == "NCHW":
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op("maxpool2d", [xt.name], **kw))
        return ctx.op("maxpool2d", ctx.inputs[:1], **kw)

    @R("AvgPool")
    def _avgpool(ctx):
        df, hw = _layout(ctx)
        ks = ctx.attr("ksize", [1, 2, 2, 1])
        st = ctx.attr("strides", [1, 2, 2, 1])
        pad = _check_padding(ctx)
        kw = dict(kernel=(int(ks[hw[0]]), int(ks[hw[1]])),
                  strides=(int(st[hw[0]]), int(st[hw[1]])),
                  padding="SAME" if pad == "SAME" else "VALID")
        if df == "NCHW":
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op("avgpool2d", [xt.name], **kw))
        return ctx.op("avgpool2d", ctx.inputs[:1], **kw)

    def _diag_guard(ctx, roles):
        """MatrixDiag/Part/SetDiag V2/V3 extra operands — only the
        defaults map onto the square diag ops: k must be 0 (the main
        diagonal; -1 here means SUB-diagonal, not a default), num_rows/
        num_cols must be the -1 'infer' sentinel OR equal the natural
        diagonal length (converters often materialize concrete shapes;
        an explicit size that pads/truncates would be miscompiled by
        matrix_diag), padding_value must be 0."""
        diag_len = None
        p = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
        if p is not None and p[0].shape and \
                p[0].shape[-1] == p[1].shape[-1]:
            diag_len = int(p[0].shape[-1])
        base = len(ctx.inputs) - len(roles)
        for i, role in enumerate(roles):
            v = np.atleast_1d(ctx.static_np(base + i))
            ok = np.all(v == 0) if role in ("k", "padding") \
                else (np.all(v == -1)
                      or (diag_len is not None
                          and np.all(v == diag_len)))
            if not ok:
                raise TFImportError(
                    f"{ctx.node.name} ({ctx.node.op}): {role}="
                    f"{v.tolist()} — only the k=0 main-diagonal "
                    "inferred-size zero-padding form is importable")

    @R("MatrixDiag", "MatrixDiagV2", "MatrixDiagV3")
    def _matrix_diag(ctx):
        # V2/V3 operands: diagonal, k, num_rows, num_cols, padding
        _diag_guard(ctx, ["k", "rows", "cols", "padding"]
                    [:len(ctx.inputs) - 1])
        return ctx.op("matrix_diag", ctx.inputs[:1])

    @R("MatrixDiagPart", "MatrixDiagPartV2", "MatrixDiagPartV3")
    def _matrix_diag_part(ctx):
        # V2/V3 operands: input, k, padding_value
        _diag_guard(ctx, ["k", "padding"][:len(ctx.inputs) - 1])
        return ctx.op("diag_part", ctx.inputs[:1])

    @R("MatrixSetDiag", "MatrixSetDiagV2", "MatrixSetDiagV3")
    def _matrix_set_diag(ctx):
        # V2/V3 operands: input, diagonal, k
        _diag_guard(ctx, ["k"][:len(ctx.inputs) - 2])
        return ctx.op("matrix_set_diag", ctx.inputs[:2])

    @R("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
    def _fused_bn(ctx):
        if ctx.attr("is_training", True):
            raise TFImportError(
                f"{ctx.node.name}: FusedBatchNorm with is_training=True — "
                "freeze the graph for inference first")
        eps = float(ctx.attr("epsilon", 1e-3))
        if ctx.attr("data_format", "NHWC") == "NCHW":
            # scale/offset/mean/var are per-channel vectors — layout
            # only moves the data tensor
            return _nchw_sandwich(
                ctx, lambda xt: ctx.sd._op(
                    "batch_norm",
                    [xt.name] + [v.name for v in ctx.inputs[1:5]],
                    eps=eps))
        return ctx.op("batch_norm", ctx.inputs[:5], eps=eps)


def _register_extended_mappers():
    """Scientific/segment/linalg/layout mappers over ALREADY-registered
    ops (round-3 breadth: graphs using tf.math special functions,
    cumulative ops, segment ops, top-k, space/depth layout ops import
    without custom work — reference: the TFGraphTestAllSameDiff battery
    spans these op families)."""
    R = OpMappingRegistry.register

    for tf_op, our in [("Asin", "asin"), ("Acos", "acos"),
                       ("Atan", "atan"), ("Asinh", "asinh"),
                       ("Acosh", "acosh"), ("Atanh", "atanh"),
                       ("Lgamma", "lgamma"), ("Digamma", "digamma"),
                       ("Erfinv", "erfinv"), ("Rint", "rint"),
                       ("Expm1", "expm1"), ("IsFinite", "is_finite"),
                       ("Invert", "bitwise_not"),
                       ("InvertPermutation", "invert_permutation"),
                       ("Cholesky", "cholesky"),
                       ("MatrixDeterminant", "matrix_determinant"),
                       ("L2Loss", "l2_loss")]:
        R(tf_op)(lambda ctx, _o=our: ctx.op(_o, ctx.inputs[:1]))

    for tf_op, our in [("Atan2", "atan2"), ("Igamma", "igamma"),
                       ("Igammac", "igammac"), ("Zeta", "zeta"),
                       ("Polygamma", "polygamma"), ("Xlogy", "xlogy"),
                       ("Xdivy", "xdivy"), ("Xlog1py", "xlog1py"),
                       ("TruncateDiv", "truncatediv"),
                       ("TruncateMod", "fmod"),
                       ("DivNoNan", "divide_no_nan"),
                       ("LeftShift", "shift_left"),
                       ("RightShift", "shift_right"),
                       ("BitwiseAnd", "bitwise_and"),
                       ("BitwiseOr", "bitwise_or"),
                       ("BitwiseXor", "bitwise_xor"),
                       ("Cross", "cross")]:
        R(tf_op)(lambda ctx, _o=our: ctx.op(_o, ctx.inputs[:2]))

    R("Betainc")(lambda ctx: ctx.op("betainc", ctx.inputs[:3]))
    R("ClipByValue")(lambda ctx: ctx.op("clip_by_value",
                                        ctx.inputs[:3]))

    for tf_op, our in [("Cumsum", "cumsum"), ("Cumprod", "cumprod")]:
        def _cum(ctx, _o=our):
            return ctx.op(_o, ctx.inputs[:1],
                          axis=int(ctx.static_np(1)),
                          exclusive=bool(ctx.attr("exclusive", False)),
                          reverse=bool(ctx.attr("reverse", False)))
        R(tf_op)(_cum)

    @R("TopKV2")
    def _topk(ctx):
        return ctx.op("top_k", ctx.inputs[:1], n_out=2,
                      k=int(ctx.static_np(1)),
                      sorted=bool(ctx.attr("sorted", True)))

    @R("InTopK", "InTopKV2")
    def _in_top_k(ctx):
        k = (int(ctx.static_np(2)) if ctx.node.op == "InTopKV2"
             else int(ctx.attr("k")))
        return ctx.op("in_top_k", ctx.inputs[:2], k=k)

    @R("ReverseV2")
    def _reverse_v2(ctx):
        dims = [int(d) for d in np.atleast_1d(ctx.static_np(1))]
        return ctx.op("reverse", ctx.inputs[:1], dimensions=dims)

    @R("ReverseSequence")
    def _reverse_seq(ctx):
        return ctx.op("reverse_sequence", ctx.inputs[:2],
                      seq_axis=int(ctx.attr("seq_dim", 1)),
                      batch_axis=int(ctx.attr("batch_dim", 0)))

    for tf_op, our in [("SpaceToDepth", "space_to_depth"),
                       ("DepthToSpace", "depth_to_space")]:
        def _s2d(ctx, _o=our):
            if ctx.attr("data_format", "NHWC") != "NHWC":
                raise TFImportError(f"{ctx.node.name}: NHWC only")
            return ctx.op(_o, ctx.inputs[:1],
                          block_size=int(ctx.attr("block_size")))
        R(tf_op)(_s2d)

    @R("SpaceToBatchND")
    def _s2b_nd(ctx):
        return ctx.op(
            "space_to_batch_nd", ctx.inputs[:1],
            block_shape=[int(v) for v in ctx.static_np(1)],
            paddings=[[int(a), int(b)] for a, b in ctx.static_np(2)])

    @R("BatchToSpaceND")
    def _b2s_nd(ctx):
        return ctx.op(
            "batch_to_space_nd", ctx.inputs[:1],
            block_shape=[int(v) for v in ctx.static_np(1)],
            crops=[[int(a), int(b)] for a, b in ctx.static_np(2)])

    # sorted segment ops: segment_ids must be a constant so the output
    # size (max id + 1) is static under jit
    for tf_op, our in [("SegmentSum", "segment_sum"),
                       ("SegmentMean", "segment_mean"),
                       ("SegmentMax", "segment_max"),
                       ("SegmentMin", "segment_min"),
                       ("SegmentProd", "segment_prod")]:
        def _seg(ctx, _o=our):
            ids = ctx.static_np(1)
            return ctx.op(_o, ctx.inputs[:2],
                          num_segments=int(np.max(ids)) + 1)
        R(tf_op)(_seg)

    for tf_op, our in [("UnsortedSegmentSum", "unsorted_segment_sum"),
                       ("UnsortedSegmentMax", "unsorted_segment_max"),
                       ("UnsortedSegmentMin", "unsorted_segment_min"),
                       ("UnsortedSegmentProd",
                        "unsorted_segment_prod")]:
        def _useg(ctx, _o=our):
            return ctx.op(_o, ctx.inputs[:2],
                          num_segments=int(ctx.static_np(2)))
        R(tf_op)(_useg)

    @R("MatrixBandPart")
    def _band_part(ctx):
        return ctx.op("matrix_band_part", ctx.inputs[:1],
                      num_lower=int(ctx.static_np(1)),
                      num_upper=int(ctx.static_np(2)))

    @R("MatrixInverse")
    def _matrix_inverse(ctx):
        if ctx.attr("adjoint", False):
            raise TFImportError(
                f"{ctx.node.name}: MatrixInverse adjoint=True "
                "not supported")
        return ctx.op("matrix_inverse", ctx.inputs[:1])

    @R("LinSpace")
    def _linspace(ctx):
        return ctx.op("linspace", [],
                      start=float(ctx.static_np(0)),
                      stop=float(ctx.static_np(1)),
                      num=int(ctx.static_np(2)))

    @R("Diag")
    def _tf_diag(ctx):
        p = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
        if p is None or len(p[0].shape) != 1:
            raise TFImportError(
                f"{ctx.node.name}: Diag mapped for rank-1 input only")
        return ctx.op("matrix_diag", ctx.inputs[:1])

    @R("DiagPart")
    def _tf_diag_part(ctx):
        p = ctx.avals.get(ctx.inputs[0].name) if ctx.avals else None
        if p is None or len(p[0].shape) != 2:
            raise TFImportError(
                f"{ctx.node.name}: DiagPart mapped for rank-2 input "
                "only")
        return ctx.op("diag_part", ctx.inputs[:1])

    @R("Bincount", "DenseBincount")
    def _bincount(ctx):
        binary = bool(ctx.attr("binary_output", False))
        size = int(ctx.static_np(1))
        # weights may be RUNTIME-computed (only size must be static);
        # the no-weights case is an EMPTY tensor, detected by shape —
        # via the aval (works for traced tensors) or the const value
        has_w = False
        if len(ctx.inputs) > 2 and ctx.inputs[2] is not None:
            wv = ctx._static[2]
            p = ctx.avals.get(ctx.inputs[2].name) if ctx.avals else None
            if p is not None:
                has_w = int(np.prod(p[0].shape, dtype=np.int64)) > 0
            elif wv is not None:
                has_w = np.asarray(wv).size > 0
            else:
                # no static value and no aval: runtime-computed weights.
                # The NO-weights encoding is always a constant empty
                # tensor (caught above), so unknown => real weights.
                has_w = True
        if binary and has_w:
            raise TFImportError(
                f"{ctx.node.name}: binary_output with weights is "
                "undefined in TF as well")
        ins = [ctx.inputs[0]] + ([ctx.inputs[2]] if has_w else [])
        return ctx.op("bincount", ins, minlength=size,
                      binary_output=binary)

    @R("Bucketize")
    def _bucketize(ctx):
        bnd = np.asarray([float(v) for v in ctx.attr("boundaries")],
                         np.float32)
        c = ctx.sd.constant(f"{ctx.node.name}_boundaries", bnd)
        return ctx.op("searchsorted", [c, ctx.inputs[0]], side="right")


_register_standard_mappers()
_register_extended_mappers()


# The ops these mappers emit by TF attr convention (tf_strided_slice,
# tf_fill, erfc, ...) are registered in deeplearning4j_tpu.ops.tf_compat
# so graph LOADING never needs this module.
from deeplearning4j_tpu.ops import tf_compat as _tf_compat  # noqa: E402,F401

OpMappingRegistry.register("Erfc")(
    lambda ctx: ctx.op("erfc", ctx.inputs[:1]))


def _register_round4_tail():
    R = OpMappingRegistry.register

    @R("Einsum")
    def _einsum(ctx):
        return ctx.op("einsum", ctx.inputs,
                      equation=ctx.attr("equation"))

    @R("MirrorPad")
    def _mirror_pad(ctx):
        mode = ctx.attr("mode", "REFLECT")  # _decode_attrs gives str
        pads = [[int(a), int(b)] for a, b in ctx.static_np(1)]
        return ctx.op("mirror_pad", ctx.inputs[:1], paddings=pads,
                      reflect=(mode == "REFLECT"))

    @R("Roll")
    def _roll(ctx):
        shift = [int(s) for s in np.atleast_1d(ctx.static_np(1))]
        axis = [int(a) for a in np.atleast_1d(ctx.static_np(2))]
        return ctx.op("roll", ctx.inputs[:1], shift=shift, axis=axis)

    @R("TensorScatterUpdate")
    def _tensor_scatter_update(ctx):
        return ctx.op("scatter_nd_update", ctx.inputs[:3])

    @R("TensorScatterAdd")
    def _tensor_scatter_add(ctx):
        return ctx.op("scatter_nd_add", ctx.inputs[:3])

    @R("PreventGradient")
    def _prevent_gradient(ctx):
        # inference-time identity (the gradient barrier only matters
        # to TF's own autodiff; our import differentiates the WHOLE
        # rebuilt graph, where stop_gradient is the closest analog)
        return ctx.op("stop_gradient", ctx.inputs[:1])

    @R("SparseSoftmaxCrossEntropyWithLogits")
    def _sparse_softmax_ce(ctx):
        # TF returns (loss_per_example, backprop); graphs consume #0
        return ctx.op("sparse_softmax_cross_entropy", ctx.inputs[:2])


_register_round4_tail()


# ------------------------------------------------- shape-subgraph folding
class _PartialEval:
    """Import-time abstract interpreter for SHAPE-COMPUTATION subgraphs.

    Real frozen graphs (e.g. a full BERT-base, SURVEY.md §3.4) compute
    reshape targets dynamically: Shape -> StridedSlice -> Pack/Prod ->
    Reshape, with the batch dim unknown. The reference's importer folds
    these through its own shape inference; here each node's value is
    evaluated as an int64 array with DYN marking unknown dims. Shapes
    come from TWO-PROBE abstract evaluation: the importer propagates
    ``jax.eval_shape`` results with every unknown (None) placeholder dim
    set to 2 in one probe and 3 in the other — a dim whose two probe
    values agree is static, one that differs is DYN. A folded value with
    no DYN is a plain constant; Reshape accepts exactly one DYN as -1.
    """

    def __init__(self):
        # provenance registry: sentinel value -> (tensor var name, dim)
        self.dyn_prov: Dict[int, Tuple[str, int]] = {}
        self._by_src: Dict[Tuple[str, int], int] = {}
        self._next = int(np.iinfo(np.int64).min) + 1000

    def _sentinel(self, var_name: str, dim: int) -> np.int64:
        key = (var_name, dim)
        if key not in self._by_src:
            self._by_src[key] = self._next
            self.dyn_prov[self._next] = key
            self._next += 1
        return np.int64(self._by_src[key])

    def eval(self, node, attrs, in_partials: List[Optional[np.ndarray]],
             in_shape_pairs: List[Optional[Tuple[tuple, tuple]]],
             in_var_names: List[str]) -> Optional[np.ndarray]:
        op = node.op
        try:
            if op == "Shape":
                pair = in_shape_pairs[0] if in_shape_pairs else None
                if pair is None:
                    return None
                s2, s3 = pair
                if len(s2) != len(s3):
                    return None
                return np.array(
                    [a if a == b else self._sentinel(in_var_names[0], i)
                     for i, (a, b) in enumerate(zip(s2, s3))],
                    np.int64)
            vals = in_partials

            def _int(v):
                return (v is not None
                        and np.asarray(v).dtype.kind in "iu")

            if op in ("Identity", "Snapshot", "StopGradient"):
                return vals[0] if _int(vals[0]) else None
            if op == "Cast":
                # only int->int casts keep a foldable value; a float
                # target would silently truncate if folded
                if _int(vals[0]) and str(attrs.get("DstT", "")).startswith(
                        ("int", "uint")):
                    return vals[0]
                return None
            if op in ("Add", "AddV2", "Sub", "Mul", "Maximum", "Minimum",
                      "FloorDiv"):
                a, b = vals[0], vals[1]
                if not (_int(a) and _int(b)):
                    return None
                a = np.asarray(a, np.int64)
                b = np.asarray(b, np.int64)
                fn = {"Add": np.add, "AddV2": np.add, "Sub": np.subtract,
                      "Mul": np.multiply, "Maximum": np.maximum,
                      "Minimum": np.minimum,
                      "FloorDiv": np.floor_divide}[op]
                out = fn(a, b)
                dyn = _is_dyn(a) | _is_dyn(b)
                out = np.where(np.broadcast_to(dyn, out.shape), DYN, out)
                return out.astype(np.int64)
            if op == "Pack":
                if not all(_int(v) for v in vals):
                    return None
                axis = int(attrs.get("axis", 0))
                return np.stack([np.asarray(v, np.int64) for v in vals],
                                axis=axis)
            if op == "ConcatV2":
                if not all(_int(v) for v in vals):
                    return None
                axis = int(np.asarray(vals[-1]))
                return np.concatenate(
                    [np.atleast_1d(np.asarray(v, np.int64))
                     for v in vals[:-1]], axis=axis)
            if op == "Prod":
                a, ax = vals[0], vals[1]
                if not (_int(a) and _int(ax)):
                    return None
                a = np.asarray(a, np.int64)
                if np.any(_is_dyn(a)):
                    return np.asarray(DYN)
                # axis=() is TF's identity reduction — keep it, don't
                # collapse to a full (axis=None) reduction
                return np.prod(a, axis=tuple(int(x) for x in
                                             np.atleast_1d(ax)),
                               keepdims=bool(attrs.get("keep_dims", False))
                               ).astype(np.int64)
            if op in ("GatherV2", "Gather"):
                a, idxs = vals[0], vals[1]
                if not (_int(a) and _int(idxs)):
                    return None
                axis = vals[2] if len(vals) > 2 else 0
                if int(attrs.get("batch_dims", 0)) != 0 or \
                        axis is None or int(np.asarray(axis)) != 0:
                    return None  # only axis-0, no batch_dims folding
                return np.take(np.asarray(a, np.int64),
                               np.asarray(idxs, np.int64), axis=0)
            if op == "Range":
                if any(not _int(v) or np.any(_is_dyn(v))
                       for v in vals[:3]):
                    return None
                return np.arange(int(vals[0]), int(vals[1]),
                                 int(vals[2]), dtype=np.int64)
            if op == "Squeeze":
                if not _int(vals[0]):
                    return None
                v = np.asarray(vals[0])
                dims = tuple(int(d) for d in
                             attrs.get("squeeze_dims",
                                       attrs.get("axis", [])) or ())
                if not dims:
                    return np.squeeze(v)
                try:  # axis on a non-unit dim: TF errors; don't fold
                    return np.squeeze(
                        v, axis=tuple(d % max(v.ndim, 1) for d in dims))
                except ValueError:
                    return None
            if op == "ExpandDims":
                if not (_int(vals[0]) and _int(vals[1])):
                    return None
                return np.expand_dims(np.asarray(vals[0], np.int64),
                                      int(vals[1]))
            if op == "StridedSlice":
                a = vals[0]
                if not _int(a) or any(
                        not _int(v) or np.any(_is_dyn(v))
                        for v in vals[1:4]):
                    # dynamic begin/end/stride sentinels would clamp to
                    # array bounds and fold a confidently wrong slice
                    return None
                a = np.atleast_1d(np.asarray(a, np.int64))
                if a.ndim != 1:
                    return None
                begin = np.atleast_1d(vals[1])
                end = np.atleast_1d(vals[2])
                strides = np.atleast_1d(vals[3])
                bm = int(attrs.get("begin_mask", 0))
                em = int(attrs.get("end_mask", 0))
                sm = int(attrs.get("shrink_axis_mask", 0))
                if int(attrs.get("ellipsis_mask", 0)) or \
                        int(attrs.get("new_axis_mask", 0)):
                    return None
                b = None if (bm & 1) else int(begin[0])
                e = None if (em & 1) else int(end[0])
                out = a[slice(b, e, int(strides[0]))]
                if sm & 1:
                    return out[0] if out.size else None
                return out
        except Exception:
            return None
        return None


# ----------------------------------------------------------------- import
class _Walker:
    """One import scope: the top-level GraphDef, a FunctionDef body, or
    a control-flow sub-graph (reference: ImportGraph walks the graph and
    its function library; AbstractSession owns frames — here frames are
    RECONSTRUCTED at import into while_loop/if_cond ops so the whole
    graph still compiles to one XLA executable, SURVEY.md §3.4)."""

    def __init__(self, sd: SameDiff, library=None, pe=None):
        self.sd = sd
        self.library = library or {}
        self.pe = pe
        # tensor key ("node" / "node:k") -> SDVariable
        self.tensors: Dict[str, SDVariable] = {}
        self.const_vals: Dict[str, np.ndarray] = {}
        # node name -> import-time folded value (may contain DYN)
        self.partials: Dict[str, np.ndarray] = {}
        # SDVariable name -> (aval under probe batch=2, probe batch=3)
        self.avals: Dict[str, Tuple[Any, Any]] = {}
        # tensor key -> {pred var name: branch value} (v1 Switch/Merge
        # lowering; bool Switch uses 0/1, _SwitchN uses the branch int)
        self.branch_tags: Dict[str, Dict[str, Any]] = {}
        # pred var name -> "bool" (Switch) | "int" (_SwitchN index)
        self.pred_kinds: Dict[str, str] = {}
        self.nodes_by_name: Dict[str, Any] = {}

    # ------------------------------------------------------------ helpers
    @staticmethod
    def resolve(ref: str) -> Tuple[str, int]:
        if ":" in ref:
            name, idx = ref.rsplit(":", 1)
            return name, int(idx)
        return ref, 0

    def lookup(self, ref: str) -> SDVariable:
        src, idx = self.resolve(ref)
        key = f"{src}:{idx}" if idx else src
        if key not in self.tensors and f"{src}:{idx}" in self.tensors:
            key = f"{src}:{idx}"
        if key not in self.tensors:
            raise TFImportError(f"unresolved tensor ref {ref!r}")
        return self.tensors[key]

    def _propagate_avals(self, from_idx: int) -> None:
        """Two-probe abstract shape eval for ops appended since
        from_idx (mappers may emit several chained ops). Gated on pe:
        importGraph enables it for graphs with shape subgraphs, control
        flow, or runtime indexing; control-flow sub-imports always have
        it (dynamic StridedSlice detection needs ranks/dtypes)."""
        if self.pe is None:
            return
        import jax

        from deeplearning4j_tpu.ops.registry import get_op

        for opnode in self.sd._ops[from_idx:]:
            fn = get_op(opnode.op_name)
            pair = []
            for probe in (0, 1):
                ins = []
                for iname in opnode.inputs:
                    if iname in self.avals:
                        ins.append(self.avals[iname][probe])
                    elif iname in self.sd._arrays:
                        a = self.sd._arrays[iname]
                        ins.append(jax.ShapeDtypeStruct(
                            tuple(a.shape), a.dtype))
                    else:
                        ins = None
                        break
                if ins is None:
                    pair = None
                    break
                try:
                    out = jax.eval_shape(
                        lambda *a: fn(*a, **opnode.attrs), *ins)
                except Exception as _e:
                    import os as _os
                    if _os.environ.get("DL4J_TF_IMPORT_DEBUG"):
                        print(f"aval-fail {opnode.op_name} "
                              f"{opnode.outputs[0][-60:]}: "
                              f"{type(_e).__name__}: {_e}")
                    pair = None
                    break
                pair.append(list(out) if isinstance(out, (list, tuple))
                            else [out])
            if pair is None:
                continue
            for k, on in enumerate(opnode.outputs):
                if k < len(pair[0]):
                    self.avals[on] = (pair[0][k], pair[1][k])

    def _gather_tags(self, node) -> Dict[str, bool]:
        """Union of branch tags over a node's data AND control inputs
        (v1 cond pipes branch constants to Merge with only a control
        edge from the branch pivot, so control edges carry tags too)."""
        tags: Dict[str, bool] = {}
        conflicted: set = set()
        for ref in node.input:
            key = ref
            if ref.startswith("^"):
                key = ref[1:]
            else:
                src, idx = self.resolve(ref)
                key = f"{src}:{idx}" if idx else src
                if key not in self.branch_tags and \
                        f"{src}:{idx}" in self.branch_tags:
                    key = f"{src}:{idx}"
            t = self.branch_tags.get(key)
            if t:
                for p, b in t.items():
                    if p in conflicted:
                        continue
                    if p in tags and tags[p] != b:
                        # both branches feed this node: it is post-merge
                        # or pred-side; the tag cancels STICKILY (a
                        # later same-pred input must not re-add it)
                        tags.pop(p)
                        conflicted.add(p)
                    else:
                        tags[p] = b
        return tags

    # --------------------------------------------------------------- walk
    def walk(self, nodes: Sequence[Any]) -> None:
        from deeplearning4j_tpu.modelimport.tensorflow.cf_import import (
            plan_v1_frames,
        )

        for n in nodes:
            self.nodes_by_name.setdefault(n.name, n)
        skip, exit_map, plans = plan_v1_frames(self, nodes)
        emitted: Dict[str, Tuple[SDVariable, ...]] = {}
        for node in nodes:
            if node.name in exit_map:
                frame_key, var_idx = exit_map[node.name]
                if frame_key not in emitted:
                    emitted[frame_key] = plans[frame_key].emit(self)
                v = emitted[frame_key][var_idx]
                # downstream refs use the Exit node's name
                if node.name not in self.sd._vars:
                    old = v.name
                    v.rename(node.name)
                    if old in self.avals:
                        self.avals[node.name] = self.avals.pop(old)
                self.tensors[node.name] = v
                self.tensors[node.name + ":0"] = v
                continue
            if node.name in skip:
                continue
            self.process_node(node)

    def process_node(self, node) -> None:
        import jax

        from deeplearning4j_tpu.modelimport.tensorflow import cf_import

        sd = self.sd
        attrs = _decode_attrs(node)
        if node.op in ("NoOp", "Assert"):
            # Assert: runtime-check node, consumed via control edges
            # only — the reference importer likewise drops it.
            return
        if node.op == "Const":
            from tensorflow.python.framework import tensor_util

            val = tensor_util.MakeNdarray(node.attr["value"].tensor)
            if val.dtype.kind in "OSU":
                # string consts (Assert messages etc.) have no JAX
                # representation; their only consumers are dropped
                # check nodes
                self.const_vals[node.name] = val
                return
            v = sd.constant(node.name, val)
            if v.name != node.name:
                raise TFImportError(f"duplicate node name {node.name!r}")
            self.tensors[node.name] = v
            self.tensors[node.name + ":0"] = v
            self.const_vals[node.name] = val
            aval = jax.ShapeDtypeStruct(tuple(val.shape), val.dtype)
            self.avals[v.name] = (aval, aval)
            return
        if node.op in ("Placeholder", "PlaceholderWithDefault"):
            shape = attrs.get("shape")
            shape = [None if d in (-1, None) else int(d)
                     for d in shape] if shape else None
            v = sd.placeholder(node.name, shape=shape,
                               dtype=attrs.get("dtype", "float32"))
            self.tensors[node.name] = v
            self.tensors[node.name + ":0"] = v
            if shape is not None:
                dt = np.dtype(attrs.get("dtype", "float32"))
                # distinct probe pairs PER DIM INDEX (dim i ->
                # (2+2i, 3+2i)) so two dynamic dims of one
                # placeholder (e.g. [None, None] batch+seq) stay
                # distinguishable in resolve_dyn_dim; the same dim
                # index across placeholders shares a pair so
                # cross-placeholder elementwise ops still probe
                # consistently.
                self.avals[v.name] = tuple(
                    jax.ShapeDtypeStruct(
                        tuple(p + 2 * i if d is None else d
                              for i, d in enumerate(shape)), dt)
                    for p in (2, 3))
            return

        in_vars: List[SDVariable] = []
        statics: List[Optional[np.ndarray]] = []
        in_refs: List[Tuple[str, int]] = []
        for ref in node.input:
            if ref.startswith("^"):  # control edge: ordering only
                continue
            src, idx = self.resolve(ref)
            key = f"{src}:{idx}" if idx else src
            if key not in self.tensors and \
                    f"{src}:{idx}" in self.tensors:
                key = f"{src}:{idx}"
            if key not in self.tensors:
                raise TFImportError(
                    f"node {node.name}: unresolved input {ref!r}")
            in_vars.append(self.tensors[key])
            sv = self.const_vals.get(src) if idx == 0 else None
            if sv is None and idx == 0:
                sv = self.partials.get(src)
            if sv is None:
                # a traced integer scalar/small vector (loop counter,
                # runtime begin index) becomes a DYN-valued partial so
                # shape/index subgraphs fold around it and mappers with
                # a dynamic fallback (StridedSlice) can engage it
                p = self.avals.get(self.tensors[key].name)
                if p is not None and p[0].shape == p[1].shape and \
                        np.issubdtype(p[0].dtype, np.integer) and \
                        len(p[0].shape) <= 1 and \
                        int(np.prod(p[0].shape, dtype=np.int64)) <= 16:
                    sv = np.full(p[0].shape, DYN, np.int64)
            statics.append(sv)
            in_refs.append((src, idx))

        # v1 cond lowering + functional (v2) control flow live in
        # cf_import; they need walker state, not just a _Ctx
        if node.op in cf_import.WALKER_OPS:
            mapper_trace.record("tf", node.op)
            n_before = len(sd._ops)
            cf_import.WALKER_OPS[node.op](self, node, in_vars, in_refs)
            self._propagate_avals(n_before)
            return

        if self.pe is not None:
            shape_pairs = []
            for v in in_vars:
                p = self.avals.get(v.name)
                shape_pairs.append(
                    (tuple(p[0].shape), tuple(p[1].shape))
                    if p is not None else None)
            pv = self.pe.eval(node, attrs, statics, shape_pairs,
                              [v.name for v in in_vars])
            if pv is not None:
                self.partials[node.name] = np.asarray(pv)

        mapper = OpMappingRegistry.get(node.op)
        ctx = _Ctx(sd, node, in_vars, statics, attrs, pe=self.pe,
                   avals=self.avals)
        n_ops_before = len(sd._ops)
        out = mapper(ctx)
        if isinstance(out, tuple):
            for k, v in enumerate(out):
                self.tensors[f"{node.name}:{k}"] = v
            self.tensors[node.name] = out[0]
        else:
            self.tensors[node.name] = out
            self.tensors[node.name + ":0"] = out
            # TF names the node's output after the node; align our
            # variable name so sd.output(..., ["node_name"]) works
            if out.name != node.name:
                out.rename(node.name)
        self._propagate_avals(n_ops_before)
        tags = self._gather_tags(node)
        if tags:
            for key in ([node.name, node.name + ":0"] +
                        [f"{node.name}:{k}" for k in range(
                            1, len(out) if isinstance(out, tuple) else 1)]):
                self.branch_tags[key] = dict(tags)


class TFGraphMapper:
    """reference: TFGraphMapper#importGraph / ImportGraph.importGraph."""

    @staticmethod
    def importGraph(graph_def_or_path) -> SameDiff:
        """Import a frozen GraphDef (proto object, serialized bytes, or
        .pb path) into a SameDiff graph.

        Placeholders become SameDiff placeholders; Consts become
        constants (use SameDiff.convertConstantsToVariables to fine-tune
        imported weights, as the reference does for frozen models).
        Control flow imports both ways the reference handles it
        (SURVEY.md §3.4 AbstractSession, §2.14 import framework): TF1
        Switch/Merge/Enter/Exit/NextIteration frames are reconstructed
        into while_loop/if_cond ops, and TF2 functional While/If/
        PartitionedCall map through the graph's function library.
        """
        gd = TFGraphMapper._as_graph_def(graph_def_or_path)
        sd = SameDiff()
        library = {f.signature.name: f for f in gd.library.function} \
            if gd.library.function else {}
        # two-probe shape folding + aval tracking pay ~2 eval_shape per
        # node; enable only where they can matter (shape subgraphs,
        # control flow, runtime indexing) — plain frozen graphs import
        # on the fast path
        _PE_OPS = {"Shape", "Enter", "RefEnter", "While",
                   "StatelessWhile", "If", "StatelessIf",
                   "PartitionedCall", "StatefulPartitionedCall",
                   "Switch", "Merge", "StridedSlice",
                   # aval-consuming mappers
                   "Bincount", "DenseBincount", "Diag", "DiagPart"}
        all_nodes = list(gd.node)
        lib_nodes = [nd for f in library.values() for nd in f.node_def]
        needs_pe = any(n.op in _PE_OPS for n in all_nodes) or \
            any(n.op in _PE_OPS for n in lib_nodes)
        walker = _Walker(sd, library=library,
                         pe=_PartialEval() if needs_pe else None)
        walker.walk(all_nodes)
        return sd

    @staticmethod
    def _as_graph_def(src):
        from tensorflow.core.framework import graph_pb2

        if isinstance(src, graph_pb2.GraphDef):
            return src
        if isinstance(src, bytes):
            gd = graph_pb2.GraphDef()
            gd.ParseFromString(src)
            return gd
        if isinstance(src, str):
            gd = graph_pb2.GraphDef()
            with open(src, "rb") as f:
                gd.ParseFromString(f.read())
            return gd
        # tf.Graph or function-like
        if hasattr(src, "as_graph_def"):
            return src.as_graph_def()
        raise TFImportError(f"cannot interpret {type(src)} as a GraphDef")


# Control-flow import (v1 frames, functional While/If, TensorArrays)
# registers its mappers on load; imported last so every name above is
# available to it.
from deeplearning4j_tpu.modelimport.tensorflow import cf_import  # noqa: E402,F401
